"""Elastic server resharding (docs/robustness.md "migration flow"):
versioned key→server ownership, live key migration, exactly-once handoff.

Layers under test:

- the consistent-hash ownership ring: balance, minimal movement on a
  rank join, ``fn="ring"`` routing, and bit-identical coordinates
  between Python (hashing.ring_key_hash) and the C++ engine
  (wire.h ring_key_hash via the golden shim);
- wire codecs for Op.MIGRATE_STATE / Op.WRONG_OWNER, plus symbolic op
  names in BYTEPS_CHAOS_OPS (the deterministic-test targeting knob);
- wire-level migration: the old owner ships a key's store + exactly-once
  ledger + init-token record, tombstones it, and redirects; the new
  owner serves the continued version sequence and DEDUPES a replayed
  round (no double-sum — the handoff is exactly-once);
- map-epoch skew: a worker holding a stale map pushes to the old owner,
  is redirected, waits for the new book, and its resend lands on the new
  owner (async push chase AND blocking init chase);
- migration parking: a request reaching the new owner before its state
  does parks until the MIGRATE_STATE frame lands; an evicted previous
  owner (state is gone) must NOT park — the re-init path owns rebirth;
- the native engine's ownership awareness: WRONG_OWNER replies for
  un-held keys the map homes elsewhere, held keys stay authoritative,
  MIGRATE_STATE is refused with the clean status=1 echo;
- gauges riding the heartbeat delta (server_owned_keys & co. toward the
  scheduler aggregate that tools/bps_top.py renders);
- end-to-end: a live scale-up then scale-down against a real scheduler
  with a real PSClient — bitwise pulls throughout, migration counters
  move, NO re-init generation bump, and the drained server stops itself.
"""

import json
import struct
import threading
import time

import numpy as np
import pytest

from byteps_tpu.common.config import Config
from byteps_tpu.common.hashing import (
    HashRing,
    OwnershipMap,
    assign_server,
    ring_key_hash,
)
from byteps_tpu.common.types import DataType, RequestType, get_command_type
from byteps_tpu.comm.transport import (
    Message,
    Op,
    close_socket,
    connect,
    decode_migrate_state,
    decode_wrong_owner,
    encode_fused_push,
    encode_migrate_state,
    encode_wrong_owner,
    recv_message,
    send_message,
)
from byteps_tpu.core.telemetry import counters
from byteps_tpu.server.server import PSServer
from conftest import have_native_parity_server

CMD_F32 = get_command_type(RequestType.DEFAULT_PUSH_PULL, int(DataType.FLOAT32))
F32 = int(DataType.FLOAT32)


def _key_owned_by(rank: int, ranks, vnodes: int = 64, start: int = 0) -> int:
    """Smallest key (stepping the partition-key stride) the ring homes on
    ``rank`` — deterministic, so tests pick real migration victims."""
    ring = HashRing(ranks, vnodes=vnodes)
    for k in range(start, start + (1 << 12)):
        key = k << 16
        if ring.owner(key) == rank:
            return key
    raise AssertionError(f"no key owned by rank {rank} in probe range")


def _wire_server(num_workers: int = 1, reshard: bool = True) -> PSServer:
    srv = PSServer(Config(num_worker=num_workers, num_server=1,
                          elastic_reshard=reshard))
    srv.start(register=False)
    return srv


def _init_key(socks_flags, key: int, n: int, token: int = 77):
    payload = struct.pack("!QI", n, F32)
    for i, (sock, flag) in enumerate(socks_flags):
        send_message(sock, Message(Op.INIT, key=key, seq=100 + i, flags=flag,
                                   version=token, payload=payload))
    for sock, _ in socks_flags:
        assert recv_message(sock).op == Op.INIT


def _book(epoch, ranks, servers, drain=False):
    b = {"map_epoch": epoch, "server_ranks": list(ranks),
         "servers": [list(s) for s in servers]}
    if drain:
        b["drain"] = True
    return b


def _wait(pred, timeout=10.0, msg="condition never held"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(msg)


class TestOwnershipRing:
    def test_balance(self):
        ring = HashRing([0, 1, 2], vnodes=64)
        from collections import Counter

        owners = Counter(ring.owner(k << 16) for k in range(3000))
        for r in (0, 1, 2):
            # consistent hashing is approximate; vnodes=64 keeps every
            # rank within a sane band (a broken point hash collapses
            # the whole space onto one rank — the bug this pins)
            assert owners[r] > 3000 * 0.15, owners

    def test_minimal_movement_on_join(self):
        r2 = HashRing([0, 1], vnodes=64)
        r3 = HashRing([0, 1, 2], vnodes=64)
        keys = [k << 16 for k in range(2000)]
        moved = [k for k in keys if r2.owner(k) != r3.owner(k)]
        # every re-homed key moved TO the joiner — survivors never
        # shuffle keys among themselves (the bounded-window property)
        assert moved and all(r3.owner(k) == 2 for k in moved)
        assert len(moved) < len(keys) * 0.5  # ≈ 1/3 ideally

    def test_ring_fn_routes_like_the_ring(self):
        ring = HashRing(range(3), vnodes=64)
        for k in range(0, 1 << 20, 1 << 16):
            assert assign_server(k, 3, fn="ring") == ring.owner(k)

    def test_ownership_map_carries_epoch(self):
        m = OwnershipMap([0, 2, 5], epoch=7)
        assert m.epoch == 7 and m.ranks == (0, 2, 5)
        assert m.owner(123) in (0, 2, 5)

    @pytest.mark.skipif(not have_native_parity_server(),
                        reason="native lib unavailable")
    def test_ring_key_hash_native_parity(self):
        import ctypes

        from byteps_tpu.native import get_lib

        lib = get_lib()
        if not hasattr(lib, "bps_wire_ring_hash"):
            pytest.skip("native lib predates the resharding plane")
        for k in [0, 1, 65536, 1 << 33, (1 << 40) + 17, 999 << 16]:
            assert lib.bps_wire_ring_hash(ctypes.c_uint64(k).value) == (
                ring_key_hash(k)
            ), f"ring hash diverged for key {k}"


class TestReshardCodecs:
    def test_migrate_state_roundtrip(self):
        store = np.arange(32, dtype=np.float32).tobytes()
        accum = np.full(32, 2.5, dtype=np.float32).tobytes()
        meta = {"key": 7, "epoch": 3, "dtype": "float32",
                "store_version": 5, "push_seen": {"1": 5, "2": 4},
                "init_done": {"1": 77},
                "store_nbytes": len(store), "accum_nbytes": len(accum)}
        m2, s2, a2 = decode_migrate_state(
            encode_migrate_state(meta, store, accum)
        )
        assert m2 == meta and s2 == store and a2 == accum

    def test_migrate_state_truncation_raises(self):
        store = b"x" * 64
        meta = {"key": 1, "store_nbytes": 64, "accum_nbytes": 0}
        body = encode_migrate_state(meta, store)
        with pytest.raises(ValueError):
            decode_migrate_state(body[: len(body) - 8])
        with pytest.raises(ValueError):
            decode_migrate_state(b"\x00\x00")

    def test_wrong_owner_roundtrip(self):
        assert decode_wrong_owner(encode_wrong_owner(9, 2)) == (9, 2)
        # empty / garbage bodies fall back to header-only semantics
        assert decode_wrong_owner(b"") == (0, -1)
        assert decode_wrong_owner(b"\xff\xfe") == (0, -1)

    def test_chaos_ops_accepts_symbolic_names(self, monkeypatch):
        from byteps_tpu.comm.chaos import ChaosParams

        monkeypatch.setenv("BYTEPS_CHAOS_OPS",
                           "MIGRATE_STATE, wrong_owner, 11")
        assert ChaosParams.from_env().ops == frozenset(
            {int(Op.MIGRATE_STATE), int(Op.WRONG_OWNER), int(Op.PUSH)}
        )
        monkeypatch.setenv("BYTEPS_CHAOS_OPS", "NOT_AN_OP")
        with pytest.raises(ValueError):
            ChaosParams.from_env()


class TestMigrationWire:
    """Wire-level handoff between two real Python servers."""

    def test_migration_moves_state_redirects_and_dedupes(self):
        a = _wire_server()
        b = _wire_server()
        a.rank, b.rank = 0, 1
        key = _key_owned_by(1, [0, 1])  # re-homes to b under epoch 2
        n = 16
        g1 = np.arange(n, dtype=np.float32)
        g2 = np.full(n, 3.5, dtype=np.float32)
        w = connect(a.host, a.port)
        w.settimeout(15)
        try:
            _init_key([(w, 1)], key, n)
            for ver, g in ((1, g1), (2, g2)):
                send_message(w, Message(Op.PUSH, key=key, seq=ver, flags=1,
                                        cmd=CMD_F32, version=ver,
                                        payload=g.tobytes()))
                assert recv_message(w).op == Op.PUSH
            # the scheduler's new book lands on BOTH servers (b adopts
            # the map too, so it won't park forever on its own keys)
            servers = [(a.host, a.port), (b.host, b.port)]
            book = _book(2, [0, 1], servers)
            b._adopt_book(dict(book, rank=1))
            a._adopt_book(dict(book, rank=0))
            _wait(lambda: key in b._keys
                  and b._keys[key].store is not None,
                  msg="migration never landed on the new owner")
            st = b._keys[key]
            assert st.store_version == 2
            assert st.push_seen.get(1) == 2      # ledger traveled
            assert st.init_done.get(1) is not None  # token record traveled
            np.testing.assert_array_equal(
                st.store, g2
            )  # round-2 publish traveled bitwise
            assert a._keys[key].migrated_to == 1  # tombstone at old owner
            assert a._keys[key].store is None     # bulk freed
            # stale-map push to the OLD owner redirects with the epoch
            send_message(w, Message(Op.PUSH, key=key, seq=9, flags=1,
                                    cmd=CMD_F32, version=3,
                                    payload=g1.tobytes()))
            r = recv_message(w)
            assert r.op == Op.WRONG_OWNER and r.version == 2
            assert decode_wrong_owner(r.payload) == (2, 1)
            # exactly-once handoff: replaying the ALREADY-SUMMED round 2
            # at the new owner dedupes — the sum must not move
            wb = connect(b.host, b.port)
            wb.settimeout(15)
            send_message(wb, Message(Op.PUSH, key=key, seq=10, flags=1,
                                     cmd=CMD_F32, version=2,
                                     payload=g2.tobytes()))
            assert recv_message(wb).op == Op.PUSH
            send_message(wb, Message(Op.PULL, key=key, seq=11, cmd=CMD_F32,
                                     version=2))
            pull = recv_message(wb)
            assert pull.op == Op.PULL and pull.version == 2
            np.testing.assert_array_equal(
                np.frombuffer(pull.payload, dtype=np.float32), g2
            )
            # ...and the version sequence CONTINUES in place: round 3
            send_message(wb, Message(Op.PUSH, key=key, seq=12, flags=1,
                                     cmd=CMD_F32, version=3,
                                     payload=g1.tobytes()))
            assert recv_message(wb).op == Op.PUSH
            send_message(wb, Message(Op.PULL, key=key, seq=13, cmd=CMD_F32,
                                     version=3))
            np.testing.assert_array_equal(
                np.frombuffer(recv_message(wb).payload, dtype=np.float32), g1
            )
            close_socket(wb)
        finally:
            close_socket(w)
            a.stop()
            b.stop()

    def test_fused_frame_redirects_whole_frame_once(self):
        a = _wire_server()
        a.rank = 0
        key = _key_owned_by(1, [0, 1])
        w = connect(a.host, a.port)
        w.settimeout(15)
        try:
            # key never held here + map homes it on rank 1 → redirect;
            # the FRAME gets ONE WrongOwner on its own seq (abort fence)
            a._adopt_book(_book(2, [0, 1], [(a.host, a.port),
                                            ("127.0.0.1", 1)]))
            g = np.ones(8, dtype=np.float32)
            frame = encode_fused_push([(key, CMD_F32, 1, g.tobytes())])
            send_message(w, Message(Op.FUSED, key=key, seq=44, flags=1,
                                    cmd=1, payload=frame))
            r = recv_message(w)
            assert r.op == Op.WRONG_OWNER and r.seq == 44
            assert decode_wrong_owner(r.payload)[1] == 1
        finally:
            close_socket(w)
            a.stop()

    def test_request_parks_until_migration_lands(self):
        b = _wire_server()
        b.rank = 1
        key = _key_owned_by(1, [0, 1])
        n = 8
        g = np.full(n, 2.0, dtype=np.float32)
        # b owns the key under the adopted map but has no state yet —
        # the previous owner (rank 0, still in the rank list) will ship
        b._adopt_book(_book(2, [0, 1], [("127.0.0.1", 1),
                                        (b.host, b.port)]))
        w = connect(b.host, b.port)
        peer = connect(b.host, b.port)  # plays the migrating old owner
        w.settimeout(15)
        peer.settimeout(15)
        try:
            send_message(w, Message(Op.PUSH, key=key, seq=1, flags=1,
                                    cmd=CMD_F32, version=2,
                                    payload=g.tobytes()))
            time.sleep(0.3)  # parked, NOT acked, NOT dropped
            store = np.arange(n, dtype=np.float32)
            meta = {"key": key, "epoch": 2, "dtype": "float32",
                    "store_version": 1, "recv_count": 0, "pushed_total": 1,
                    "push_seen": {"1": 1}, "init_done": {},
                    "compressor_kwargs": {},
                    "store_nbytes": store.nbytes, "accum_nbytes": 0}
            send_message(peer, Message(
                Op.MIGRATE_STATE, key=key, version=2,
                payload=encode_migrate_state(meta, store.tobytes()),
            ))
            assert recv_message(peer).status == 0  # installed + acked
            # the parked push wakes, sums round 2, acks
            assert recv_message(w).op == Op.PUSH
            send_message(w, Message(Op.PULL, key=key, seq=2, cmd=CMD_F32,
                                    version=2))
            np.testing.assert_array_equal(
                np.frombuffer(recv_message(w).payload, dtype=np.float32), g
            )
        finally:
            close_socket(w)
            close_socket(peer)
            b.stop()

    def test_evicted_previous_owner_does_not_park(self):
        b = _wire_server()
        b.rank = 1
        key = _key_owned_by(1, [0, 1])
        # epoch 2: {0, 1}; epoch 3: rank 0 CRASHED out — nothing will
        # ever migrate, so an uninitialized push must fail fast into the
        # worker's re-init path (dropped conn), not park to the deadline
        b._adopt_book(_book(2, [0, 1], [("127.0.0.1", 1),
                                        (b.host, b.port)]))
        b._adopt_book(_book(3, [1], [(b.host, b.port)]))
        w = connect(b.host, b.port)
        w.settimeout(5)
        try:
            send_message(w, Message(Op.PUSH, key=key, seq=1, flags=1,
                                    cmd=CMD_F32, version=1,
                                    payload=np.ones(4, np.float32).tobytes()))
            with pytest.raises((ConnectionError, OSError, TimeoutError)):
                msg = recv_message(w)
                raise AssertionError(f"expected dropped conn, got {msg.op}")
        finally:
            close_socket(w)
            b.stop()

    def test_live_key_refuses_inbound_migration_as_complete(self):
        # the stale-snapshot-resurrection guard: a key that is LIVE at
        # the receiver (installed by an earlier attempt whose ack was
        # lost, or re-created by the degraded fallback with restarted
        # version numbering) must refuse a shipment AS COMPLETE (status
        # 3 → the sender drops its copy) instead of installing a stale
        # snapshot whose higher store_version would serve old rounds
        srv = _wire_server()
        srv.rank = 0
        key = _key_owned_by(0, [0])
        n = 8
        live = np.full(n, 2.0, dtype=np.float32)
        w = connect(srv.host, srv.port)
        w.settimeout(10)
        try:
            srv._adopt_book(_book(3, [0], [(srv.host, srv.port)]))
            _init_key([(w, 1)], key, n)
            send_message(w, Message(Op.PUSH, key=key, seq=1, flags=1,
                                    cmd=CMD_F32, version=1,
                                    payload=live.tobytes()))
            assert recv_message(w).op == Op.PUSH
            stale = np.full(n, 9.0, dtype=np.float32)
            send_message(w, Message(
                Op.MIGRATE_STATE, key=key, version=2,
                payload=encode_migrate_state(
                    {"key": key, "epoch": 2, "dtype": "float32",
                     "store_version": 40, "store_nbytes": stale.nbytes,
                     "accum_nbytes": 0},
                    stale.tobytes(),
                ),
            ))
            r = recv_message(w)
            assert r.op == Op.MIGRATE_STATE and r.status == 3
            st = srv._keys[key]
            assert st.store_version == 1  # live state untouched
            np.testing.assert_array_equal(st.store, live)
        finally:
            close_socket(w)
            srv.stop()

    def test_migrate_refused_when_reshard_off(self):
        srv = _wire_server(reshard=False)
        w = connect(srv.host, srv.port)
        w.settimeout(10)
        try:
            send_message(w, Message(
                Op.MIGRATE_STATE, key=5, version=1,
                payload=encode_migrate_state(
                    {"key": 5, "store_nbytes": 0, "accum_nbytes": 0}
                ),
            ))
            r = recv_message(w)
            assert r.op == Op.MIGRATE_STATE and r.status != 0
        finally:
            close_socket(w)
            srv.stop()


class TestOptimizerStateMigration:
    """Server-side optimizer keys (docs/architecture.md "Server-side
    optimizer") migrate their rule WITH the store: slot tensors ride the
    MIGRATE_STATE frame as a raw tail after the accum blob, the step
    count and per-worker seed ledger ride the meta, and the trajectory
    at the new owner continues BITWISE — a reshard mid-run is invisible
    to the update math."""

    def test_reshard_moves_adam_slots_and_trajectory_stays_bitwise(self):
        from byteps_tpu.comm.transport import encode_server_opt_block
        from byteps_tpu.server.update_rules import canonical_hp, make_rule

        a = _wire_server()
        b = _wire_server()
        a.rank, b.rank = 0, 1
        key = _key_owned_by(1, [0, 1])  # re-homes to b under epoch 2
        n = 32
        hp = {"lr": 0.002}
        rng = np.random.default_rng(21)
        x0 = rng.standard_normal(n).astype(np.float32)
        # local reference: same rule class, same op order, 1 worker
        ref = make_rule("adam", hp, n, np.dtype(np.float32))
        ref_params = x0.copy()
        ref_t = 0

        def _ref_step(g):
            nonlocal ref_t
            ref_t += 1
            ref.apply(ref_params, g, 1, ref_t)
            return ref_params

        payload = (struct.pack("!QI", n, F32)
                   + struct.pack("!Bi", 2, -1)
                   + encode_server_opt_block("adam", canonical_hp(hp)))
        w = connect(a.host, a.port)
        w.settimeout(15)
        try:
            send_message(w, Message(Op.INIT, key=key, seq=1, flags=1,
                                    version=77, payload=payload))
            r = recv_message(w)
            assert r.op == Op.INIT and r.status == 0
            # seed round, then two Adam rounds at the OLD owner
            grads = {}
            for ver in (1, 2, 3):
                g = x0 if ver == 1 else rng.standard_normal(n).astype(
                    np.float32)
                grads[ver] = g
                send_message(w, Message(Op.PUSH, key=key, seq=ver + 1,
                                        flags=1, cmd=CMD_F32, version=ver,
                                        payload=g.tobytes()))
                assert recv_message(w).op == Op.PUSH
                if ver > 1:
                    _ref_step(g)
            send_message(w, Message(Op.PULL, key=key, seq=9, cmd=CMD_F32,
                                    version=3))
            np.testing.assert_array_equal(
                np.frombuffer(recv_message(w).payload, dtype=np.float32),
                ref_params)
            # the reshard: b adopts the key, a ships store + slots
            servers = [(a.host, a.port), (b.host, b.port)]
            book = _book(2, [0, 1], servers)
            b._adopt_book(dict(book, rank=1))
            a._adopt_book(dict(book, rank=0))
            _wait(lambda: key in b._keys
                  and b._keys[key].store is not None,
                  msg="migration never landed on the new owner")
            st = b._keys[key]
            assert st.opt_rule is not None
            assert st.opt_rule_name == "adam"
            assert st.opt_step == 3  # seed + 2 grad rounds published
            # slot tensors traveled BITWISE (m and v, in slot order)
            np.testing.assert_array_equal(st.opt_rule.m, ref.m)
            np.testing.assert_array_equal(st.opt_rule.v, ref.v)
            np.testing.assert_array_equal(st.store, ref_params)
            # the old owner tombstoned AND dropped its rule state
            assert a._keys[key].migrated_to == 1
            assert a._keys[key].opt_rule is None
            # the trajectory CONTINUES bitwise at the new owner —
            # including the bias-correction schedule (t keeps counting)
            wb = connect(b.host, b.port)
            wb.settimeout(15)
            for ver in (4, 5):
                g = rng.standard_normal(n).astype(np.float32)
                send_message(wb, Message(Op.PUSH, key=key, seq=ver + 10,
                                         flags=1, cmd=CMD_F32, version=ver,
                                         payload=g.tobytes()))
                assert recv_message(wb).op == Op.PUSH
                send_message(wb, Message(Op.PULL, key=key, seq=ver + 20,
                                         cmd=CMD_F32, version=ver))
                np.testing.assert_array_equal(
                    np.frombuffer(recv_message(wb).payload,
                                  dtype=np.float32),
                    _ref_step(g))
            # exactly-once across the handoff: replaying round 3 (summed
            # at the OLD owner, ledger traveled) cannot re-fire the rule
            step_before = b._keys[key].opt_step
            send_message(wb, Message(Op.PUSH, key=key, seq=99, flags=1,
                                     cmd=CMD_F32, version=3,
                                     payload=grads[3].tobytes()))
            assert recv_message(wb).op == Op.PUSH
            assert b._keys[key].opt_step == step_before
            np.testing.assert_array_equal(b._keys[key].store, ref_params)
            close_socket(wb)
        finally:
            close_socket(w)
            a.stop()
            b.stop()


class TestStaleMapChase:
    """Map-epoch skew: the worker-side WRONG_OWNER chase re-routes the
    RPC once the redirect's book lands (async push AND blocking init)."""

    def _cluster(self):
        cfg = Config(num_worker=1, num_server=2, elastic_reshard=True,
                     rpc_retries=4, rpc_deadline_s=2.0)
        a = PSServer(cfg)
        b = PSServer(cfg)
        a.start(register=False)
        b.start(register=False)
        a.rank, b.rank = 0, 1
        return cfg, a, b

    def _stale_client(self, cfg, a):
        from byteps_tpu.comm.ps_client import PSClient

        pc = PSClient(cfg)
        pc.rank = 0
        pc.num_servers = 1
        pc._servers = [pc._new_conn(a.host, a.port)]
        pc._server_addrs = [(a.host, a.port)]
        # the STALE world: one server, map epoch 1
        pc._install_routing(pc._servers, [0], OwnershipMap([0], epoch=1))
        return pc

    def test_async_push_chases_redirect_to_new_owner(self):
        cfg, a, b = self._cluster()
        key = _key_owned_by(1, [0, 1])
        n = 8
        g1 = np.arange(n, dtype=np.float32)
        g2 = np.full(n, 5.0, dtype=np.float32)
        pc = None
        w = connect(a.host, a.port)
        w.settimeout(15)
        try:
            _init_key([(w, 1)], key, n)
            send_message(w, Message(Op.PUSH, key=key, seq=1, flags=1,
                                    cmd=CMD_F32, version=1,
                                    payload=g1.tobytes()))
            assert recv_message(w).op == Op.PUSH
            # the cluster reshards: a ships the key to b, tombstones
            servers = [(a.host, a.port), (b.host, b.port)]
            a._adopt_book(dict(_book(2, [0, 1], servers)))
            b._adopt_book(dict(_book(2, [0, 1], servers)))
            _wait(lambda: key in b._keys and b._keys[key].store is not None,
                  msg="migration never landed")
            before = counters().get("wrong_owner_redirect")
            pc = self._stale_client(cfg, a)
            acked = threading.Event()
            pc.push(key, g2.tobytes(), F32, 2, lambda: acked.set(),
                    on_error=lambda: acked.set())

            def deliver_book():
                time.sleep(0.3)
                connb = pc._new_conn(b.host, b.port)
                pc._servers = [pc._servers[0], connb]
                pc._install_routing(pc._servers, [0, 1],
                                    OwnershipMap([0, 1], epoch=2))

            threading.Thread(target=deliver_book, daemon=True).start()
            assert acked.wait(15), "chase never resolved"
            assert counters().get("wrong_owner_redirect") > before
            # the resend landed on the NEW owner and advanced the round
            assert b._keys[key].store_version == 2
            np.testing.assert_array_equal(b._keys[key].store, g2)
        finally:
            if pc is not None:
                pc.close()
            close_socket(w)
            a.stop()
            b.stop()

    def test_blocking_init_chases_redirect(self):
        cfg, a, b = self._cluster()
        # a NEVER held this key; its map homes it on b → the blocking
        # init-push must chase and complete the barrier at b
        key = _key_owned_by(1, [0, 1])
        servers = [(a.host, a.port), (b.host, b.port)]
        a._adopt_book(dict(_book(2, [0, 1], servers)))
        b._adopt_book(dict(_book(2, [0, 1], servers)))
        pc = self._stale_client(cfg, a)
        try:
            done = threading.Event()
            err: list = []

            def do_init():
                try:
                    pc.init_tensor(key, 8, F32)
                except BaseException as e:  # noqa: BLE001
                    err.append(e)
                finally:
                    done.set()

            threading.Thread(target=do_init, daemon=True).start()
            time.sleep(0.3)
            connb = pc._new_conn(b.host, b.port)
            pc._servers = [pc._servers[0], connb]
            pc._install_routing(pc._servers, [0, 1],
                                OwnershipMap([0, 1], epoch=2))
            assert done.wait(20), "init chase never resolved"
            assert not err, f"init failed: {err}"
            assert key in b._keys and b._keys[key].store is not None
            assert key not in a._keys or a._keys[key].store is None
        finally:
            pc.close()
            a.stop()
            b.stop()


@pytest.mark.skipif(not have_native_parity_server(),
                    reason="native lib unavailable")
class TestNativeOwnership:
    """The C++ engine's ownership awareness: redirects for un-held keys
    the map homes elsewhere, held keys stay authoritative, MIGRATE_STATE
    refused cleanly (state migration is Python-engine-only)."""

    def _native(self):
        from byteps_tpu.server.server import NativePSServer

        srv = NativePSServer(Config(num_worker=1, num_server=1))
        srv.start(register=False)
        return srv

    def _install(self, srv, my_rank, epoch, ranks):
        import ctypes

        pts = HashRing(ranks, vnodes=64).points()
        hashes = (ctypes.c_uint64 * len(pts))(*[h for h, _ in pts])
        rks = (ctypes.c_int32 * len(pts))(*[r for _, r in pts])
        srv._lib.bps_native_server_set_ownership(
            srv._id, my_rank, epoch, len(pts), hashes, rks
        )

    def test_redirect_and_held_key_rules(self):
        srv = self._native()
        lib_ok = hasattr(srv._lib, "bps_native_server_set_ownership")
        if not lib_ok:
            srv.stop()
            pytest.skip("native lib predates the resharding plane")
        mine = _key_owned_by(0, [0, 1])
        theirs = _key_owned_by(1, [0, 1])
        n = 8
        g = np.arange(n, dtype=np.float32)
        w = connect(srv.host, srv.port)
        w.settimeout(15)
        try:
            # held BEFORE the map: stays authoritative afterwards
            _init_key([(w, 1)], theirs, n)
            self._install(srv, 0, 5, [0, 1])
            send_message(w, Message(Op.PUSH, key=theirs, seq=1, flags=1,
                                    cmd=CMD_F32, version=1,
                                    payload=g.tobytes()))
            assert recv_message(w).op == Op.PUSH  # pre-ship rule: served
            # owned key inits + serves normally under the map
            _init_key([(w, 1)], mine, n)
            send_message(w, Message(Op.PUSH, key=mine, seq=2, flags=1,
                                    cmd=CMD_F32, version=1,
                                    payload=g.tobytes()))
            assert recv_message(w).op == Op.PUSH
            # un-held key the map homes elsewhere: WRONG_OWNER w/ epoch
            other = _key_owned_by(1, [0, 1], start=2048)
            assert other != theirs
            send_message(w, Message(Op.PUSH, key=other, seq=3, flags=1,
                                    cmd=CMD_F32, version=1,
                                    payload=g.tobytes()))
            r = recv_message(w)
            assert r.op == Op.WRONG_OWNER and r.version == 5
            assert decode_wrong_owner(r.payload) == (5, 1)
            # ...same for INIT and PULL
            send_message(w, Message(Op.INIT, key=other, seq=4, flags=1,
                                    payload=struct.pack("!QI", n, F32)))
            assert recv_message(w).op == Op.WRONG_OWNER
            send_message(w, Message(Op.PULL, key=other, seq=5, cmd=CMD_F32,
                                    version=1))
            assert recv_message(w).op == Op.WRONG_OWNER
            # MIGRATE_STATE: clean unknown-op rejection, stream framed
            send_message(w, Message(
                Op.MIGRATE_STATE, key=other, seq=6,
                payload=encode_migrate_state(
                    {"key": other, "store_nbytes": 0, "accum_nbytes": 0}
                ),
            ))
            r = recv_message(w)
            assert r.op == Op.MIGRATE_STATE and r.status != 0
            # counter surfaced through the provider seam
            from byteps_tpu.native import native_server_counters

            assert native_server_counters(srv._id).get(
                "native_wrong_owner", 0
            ) >= 3
        finally:
            close_socket(w)
            srv.stop()

    def test_fused_member_redirect_aborts_frame(self):
        srv = self._native()
        if not hasattr(srv._lib, "bps_native_server_set_ownership"):
            srv.stop()
            pytest.skip("native lib predates the resharding plane")
        self._install(srv, 0, 7, [0, 1])
        key = _key_owned_by(1, [0, 1])
        w = connect(srv.host, srv.port)
        w.settimeout(15)
        try:
            g = np.ones(8, dtype=np.float32)
            frame = encode_fused_push([(key, CMD_F32, 1, g.tobytes())])
            send_message(w, Message(Op.FUSED, key=key, seq=31, flags=1,
                                    cmd=1, payload=frame))
            r = recv_message(w)
            assert r.op == Op.WRONG_OWNER and r.seq == 31
            assert decode_wrong_owner(r.payload) == (7, 1)
        finally:
            close_socket(w)
            srv.stop()


class TestGaugeDelta:
    """Gauges ride the heartbeat delta to the scheduler aggregate (the
    feed bps_top's ownership view renders)."""

    def test_gauge_values_ship_and_merge(self):
        from byteps_tpu.core.telemetry import MetricsRegistry

        src, agg = MetricsRegistry(), MetricsRegistry()
        src.gauge_set("server_owned_keys", 12, labels={"rank": "1"})
        d = src.delta_snapshot()
        assert {"n": "server_owned_keys", "l": [["rank", "1"]], "v": 12.0} \
            in d.get("g", [])
        agg.merge_delta(d, labels={"role": "server"})
        snap = agg.snapshot()
        assert snap["gauges"][
            'server_owned_keys{rank="1",role="server"}'
        ] == 12.0
        # unchanged → not re-shipped
        assert "g" not in (src.delta_snapshot() or {})
        # changed → ships again
        src.gauge_set("server_owned_keys", 9, labels={"rank": "1"})
        assert src.delta_snapshot()["g"][0]["v"] == 9.0

    def test_gauge_removal_ships_and_drops(self):
        from byteps_tpu.core.telemetry import MetricsRegistry

        src, agg = MetricsRegistry(), MetricsRegistry()
        src.gauge_set("server_owned_keys", 3, labels={"rank": "2"})
        agg.merge_delta(src.delta_snapshot())
        src.gauge_remove("server_owned_keys", labels={"rank": "2"})
        d = src.delta_snapshot()
        assert d.get("gr"), d
        agg.merge_delta(d)
        assert "server_owned_keys" not in str(agg.snapshot()["gauges"])

    def test_requeued_gauges_reship(self):
        from byteps_tpu.core.telemetry import MetricsRegistry

        src = MetricsRegistry()
        src.gauge_set("server_map_epoch", 4, labels={"rank": "0"})
        d = src.delta_snapshot()
        src.requeue_delta(d)  # the beat failed to send
        d2 = src.delta_snapshot()
        assert any(rec["n"] == "server_map_epoch" for rec in d2.get("g", []))

    def test_requeued_removal_does_not_kill_reappeared_series(self):
        # a removal marker from a FAILED beat must not delete a series
        # that reappeared before the next beat (the receiver applies "g"
        # then "gr" per payload, so a stale requeued "gr" would win over
        # the fresh value — e.g. a restarted server's owned-key gauge
        # silently vanishing from the aggregate)
        from byteps_tpu.core.telemetry import MetricsRegistry

        src, agg = MetricsRegistry(), MetricsRegistry()
        lbl = {"rank": "1"}
        src.gauge_set("server_owned_keys", 5, labels=lbl)
        agg.merge_delta(src.delta_snapshot())
        src.gauge_remove("server_owned_keys", labels=lbl)
        d = src.delta_snapshot()
        assert d.get("gr")
        src.requeue_delta(d)  # the removal beat failed to send
        src.gauge_set("server_owned_keys", 7, labels=lbl)  # reappears
        merged = src.delta_snapshot()
        agg.merge_delta(merged)
        snap = agg.snapshot()["gauges"]
        assert snap['server_owned_keys{rank="1"}'] == 7.0
        # the converse: a requeued VALUE must not resurrect a series
        # removed in the newer beat
        src.gauge_set("server_owned_keys", 8, labels=lbl)
        d = src.delta_snapshot()
        src.requeue_delta(d)
        src.gauge_remove("server_owned_keys", labels=lbl)
        agg.merge_delta(src.delta_snapshot())
        assert "server_owned_keys" not in str(agg.snapshot()["gauges"])

    def test_bps_top_renders_ownership(self):
        import importlib.util
        import os

        spec = importlib.util.spec_from_file_location(
            "bps_top", os.path.join(os.path.dirname(__file__), "..",
                                    "tools", "bps_top.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        cur = {
            ("byteps_cluster_map_epoch", ""): 3.0,
            ("byteps_server_owned_keys", '{rank="0"}'): 5.0,
            ("byteps_server_owned_keys", '{rank="1"}'): 7.0,
            ("byteps_server_map_epoch", '{rank="0"}'): 3.0,
            ("byteps_server_map_epoch", '{rank="1"}'): 2.0,  # lagging
        }
        out = mod.render("x", cur, {}, 1.0)
        assert "ownership map" in out and "epoch 3" in out
        assert "r0=5" in out and "r1=7*" in out  # laggard starred


class TestElasticReshardingE2E:
    """Live scale-up then scale-down against a real scheduler: bitwise
    pulls throughout, migration counters move, NO re-init generation
    bump, and the drained server stops itself."""

    def test_scale_up_then_drain_down(self, monkeypatch):
        from byteps_tpu.comm.ps_client import PSClient
        from byteps_tpu.comm.rendezvous import Scheduler

        monkeypatch.setenv("BYTEPS_ELASTIC_RESHARD", "1")
        cfg = Config(num_worker=1, num_server=2, elastic_reshard=True,
                     heartbeat_interval=0.1, rpc_retries=4,
                     rpc_deadline_s=2.0)
        sched = Scheduler(num_workers=1, num_servers=2, host="127.0.0.1")
        sched.start()
        monkeypatch.setenv("DMLC_PS_ROOT_PORT", str(sched.port))
        monkeypatch.setenv("DMLC_PS_ROOT_URI", "127.0.0.1")
        cfg = Config(num_worker=1, num_server=2, elastic_reshard=True,
                     heartbeat_interval=0.1, rpc_retries=4,
                     rpc_deadline_s=2.0, ps_root_port=sched.port)
        fleet = [PSServer(Config(num_worker=1, num_server=2,
                                 elastic_reshard=True,
                                 heartbeat_interval=0.1,
                                 ps_root_port=sched.port))
                 for _ in range(2)]
        for s in fleet:
            threading.Thread(target=s.start, daemon=True).start()
        pc = PSClient(cfg)
        extra = None
        before_moved = counters().get("migration_keys_moved")
        try:
            pc.connect()
            keys = [k << 16 for k in range(8)]
            n = 16
            for k in keys:
                pc.init_tensor(k, n, F32)
            rng = np.random.default_rng(3)
            grads = {k: rng.standard_normal(n).astype(np.float32)
                     for k in keys}

            def round_trip(ver):
                for k in keys:
                    acked = threading.Event()
                    pc.push(k, grads[k].tobytes(), F32, ver,
                            lambda e=acked: e.set())
                    assert acked.wait(15), f"push {k} v{ver} hung"
                for k in keys:
                    got = threading.Event()
                    box: list = []

                    def cb(payload, b=box, e=got):
                        b.append(payload)
                        e.set()

                    pc.pull(k, ver, cb)
                    assert got.wait(15), f"pull {k} v{ver} hung"
                    np.testing.assert_array_equal(
                        np.frombuffer(box[0], dtype=np.float32), grads[k]
                    )

            round_trip(1)
            # ---- live scale-UP to 3 (reply parks until joiner arrives)
            rt = threading.Thread(
                target=pc.request_resize, kwargs={"num_servers": 3},
                daemon=True,
            )
            rt.start()
            _wait(lambda: sched.num_servers == 3, msg="resize not adopted")
            extra = PSServer(Config(num_worker=1, num_server=3,
                                    elastic_reshard=True,
                                    heartbeat_interval=0.1,
                                    ps_root_port=sched.port))
            threading.Thread(target=extra.start, daemon=True).start()
            rt.join(timeout=20)
            assert not rt.is_alive(), "scale-up resize hung"
            _wait(lambda: counters().get("migration_keys_moved")
                  > before_moved, msg="no keys migrated on scale-up")
            round_trip(2)  # bitwise through the migration window
            assert pc.server_generation == 0  # NO re-init barrier fired
            assert pc.map_epoch >= 2 and len(pc._servers) == 3
            # ---- live scale-DOWN back to 2: the joiner drains + stops
            pc.request_resize(num_servers=2)
            _wait(lambda: extra._stop.is_set(), timeout=15,
                  msg="drained server never stopped itself")
            round_trip(3)
            assert pc.server_generation == 0
            assert counters().get("migration_keys_received") > 0
        finally:
            pc.close()
            for s in fleet:
                s.stop()
            if extra is not None:
                extra.stop()
            sched.stop()
