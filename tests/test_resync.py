"""Recovery plane (docs/robustness.md "healing flow"): round journal +
server-driven resync + init-idempotency token.

Layers under test:

- wire codecs for the Op.RESYNC_QUERY / Op.RESYNC_STATE frames;
- the bounded round journal (depth / byte-cap eviction, generation clear);
- wire-level bitwise exactness of journal replay (fused AND unfused): a
  round completed by replaying journaled payloads publishes exactly what
  the fault-free run would, and a second replay dedupes;
- the dropped-init-ACK 2-worker strand (ROADMAP): a retried INIT whose
  barrier already released is acked from the completed-barrier record;
- end-to-end in-place heal: a deterministic chaos schedule
  (BYTEPS_CHAOS_OPS + BYTEPS_CHAOS_FAULT_BUDGET) kills exactly one
  push's retry budget — the step heals via resync instead of failing;
- the api-layer fallback (engine.heal_degraded) when the client-level
  heal is unavailable;
- native-engine interop: the C++ server rejects RESYNC frames with a
  nonzero status and the stream stays framed;
- the acceptance demo: 2 worker subprocesses + 1 server, the victim's
  retry budget killed on cue — it heals in place, its peer never
  blocks, and every pulled tensor is bitwise the fault-free one.
"""

import json
import os
import socket
import struct
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from byteps_tpu.common.config import Config
from byteps_tpu.common.types import DataType, RequestType, get_command_type
from byteps_tpu.comm.journal import RoundJournal
from byteps_tpu.comm.transport import (
    Message,
    Op,
    close_socket,
    connect,
    decode_resync_query,
    decode_resync_state,
    encode_fused_push,
    encode_resync_query,
    encode_resync_state,
    recv_message,
    send_message,
)
from byteps_tpu.core.telemetry import counters
from byteps_tpu.server.server import PSServer
from conftest import (
    ENGINE_STRIPES,
    ENGINE_STRIPES_IDS,
    have_native_parity_server,
    make_ps_server,
    require_engine,
    set_stripes,
)

CMD_F32 = get_command_type(RequestType.DEFAULT_PUSH_PULL, int(DataType.FLOAT32))


class TestResyncWire:
    def test_query_roundtrip(self):
        wid, keys = decode_resync_query(encode_resync_query(3, [7, 9, 1 << 40]))
        assert wid == 3
        assert keys == [7, 9, 1 << 40]

    def test_query_empty_keys_means_all(self):
        wid, keys = decode_resync_query(encode_resync_query(1, []))
        assert wid == 1 and keys == []

    def test_state_roundtrip(self):
        states = {
            5: {"store_version": 4, "seen": 3, "recv_count": 1, "init": True},
            (1 << 33): {"store_version": 0, "seen": 0, "recv_count": 0,
                        "init": True},
        }
        out = decode_resync_state(encode_resync_state(states))
        assert out == states  # int keys restored through the JSON hop

    def test_malformed_bodies_raise(self):
        with pytest.raises(ValueError):
            decode_resync_query(b"[1, 2, 3]")
        with pytest.raises((ValueError, AttributeError)):
            decode_resync_state(b'{"keys": [1]}')


class TestRoundJournal:
    def test_depth_bound_per_key(self):
        j = RoundJournal(max_rounds=2, max_bytes=1 << 20)
        for v in (1, 2, 3):
            j.record(key=9, version=v, cmd=CMD_F32, payload=bytes([v]) * 8)
        entries = j.entries_after(9, 0)
        assert [e.version for e in entries] == [2, 3]  # round 1 evicted
        assert j.evicted == 1

    def test_byte_cap_evicts_globally_oldest(self):
        j = RoundJournal(max_rounds=8, max_bytes=100)
        j.record(1, 1, CMD_F32, b"a" * 60)
        j.record(2, 1, CMD_F32, b"b" * 60)  # key 1's round must go
        assert j.entries_after(1, 0) == []
        assert [e.version for e in j.entries_after(2, 0)] == [1]
        assert j.stats()["bytes"] == 60

    def test_replace_same_round_keeps_one_entry(self):
        j = RoundJournal(max_rounds=4, max_bytes=1 << 20)
        j.record(3, 1, CMD_F32, b"old-bytes")
        j.record(3, 1, CMD_F32, b"new", fused=True)  # unfuse fallback re-emit
        entries = j.entries_after(3, 0)
        assert len(entries) == 1 and entries[0].payload == b"new"
        assert j.stats()["bytes"] == 3

    def test_watermark_filters_absorbed_rounds(self):
        j = RoundJournal(max_rounds=4, max_bytes=1 << 20)
        for v in (1, 2, 3):
            j.record(5, v, CMD_F32, b"x")
        assert [e.version for e in j.entries_after(5, 2)] == [3]
        assert j.entries_after(5, 3) == []

    def test_clear_key_drops_generation(self):
        j = RoundJournal(max_rounds=4, max_bytes=1 << 20)
        j.record(5, 1, CMD_F32, b"x" * 10)
        j.record(6, 1, CMD_F32, b"y" * 10)
        j.clear_key(5)
        assert j.entries_after(5, 0) == []
        assert j.keys() == [6]
        assert j.stats()["bytes"] == 10


def _wire_server(num_workers: int) -> PSServer:
    srv = PSServer(Config(num_worker=num_workers, num_server=1))
    srv.start(register=False)
    return srv


def _init_key(socks_flags, key: int, n: int, tokens=None):
    """Run the init barrier for ``key`` across fake workers given as
    [(sock, worker_flag), ...]; returns after every ack."""
    payload = struct.pack("!QI", n, int(DataType.FLOAT32))
    for i, (sock, flag) in enumerate(socks_flags):
        token = tokens[i] if tokens else 0
        send_message(sock, Message(Op.INIT, key=key, seq=100 + i, flags=flag,
                                   version=token, payload=payload))
    for sock, _ in socks_flags:
        msg = recv_message(sock)
        assert msg.op == Op.INIT


class TestReplayBitwise:
    """Wire-level journal replay: completing a round from the journal
    publishes bitwise what the fault-free run would have."""

    def test_unfused_replay_completes_round_bitwise(self):
        srv = _wire_server(num_workers=2)
        KEY, N = 11, 64
        g1 = np.arange(N, dtype=np.float32)
        g2 = np.full(N, 0.5, dtype=np.float32)
        try:
            w1 = connect(srv.host, srv.port)
            w2 = connect(srv.host, srv.port)
            for s in (w1, w2):
                s.settimeout(15)
            _init_key([(w1, 1), (w2, 2)], KEY, N)
            # worker 2 journals its round-1 push but the frame is "lost"
            # (never sent).  Worker 1 pushes normally and pulls — parked.
            journal = RoundJournal(max_rounds=2, max_bytes=1 << 20)
            journal.record(KEY, 1, CMD_F32, g2.tobytes())
            send_message(w1, Message(Op.PUSH, key=KEY, seq=1, flags=1,
                                     cmd=CMD_F32, version=1,
                                     payload=g1.tobytes()))
            assert recv_message(w1).op == Op.PUSH
            send_message(w1, Message(Op.PULL, key=KEY, seq=2, cmd=CMD_F32,
                                     version=1))
            # worker 2 heals: query → server reports seen=0 → replay
            send_message(w2, Message(Op.RESYNC_QUERY, key=KEY, seq=3, flags=2,
                                     payload=encode_resync_query(2, [KEY])))
            resp = recv_message(w2)
            assert resp.op == Op.RESYNC_STATE and resp.status == 0
            state = decode_resync_state(resp.payload)
            assert state[KEY]["seen"] == 0       # our push never absorbed
            assert state[KEY]["store_version"] == 0  # round incomplete
            entries = journal.entries_after(KEY, state[KEY]["seen"])
            assert [e.version for e in entries] == [1]
            for e in entries:
                send_message(w2, Message(Op.PUSH, key=KEY, seq=4, flags=2,
                                         cmd=e.cmd, version=e.version,
                                         payload=e.payload))
                assert recv_message(w2).op == Op.PUSH
            # the round published: worker 1's parked pull answers with
            # EXACTLY the fault-free sum, and worker 2 can pull it too
            reply = recv_message(w1)
            assert reply.op == Op.PULL
            np.testing.assert_array_equal(
                np.frombuffer(reply.payload, dtype=np.float32), g1 + g2
            )
            # replaying AGAIN dedupes (exactly-once): the sum must not move
            send_message(w2, Message(Op.PUSH, key=KEY, seq=5, flags=2,
                                     cmd=CMD_F32, version=1,
                                     payload=g2.tobytes()))
            assert recv_message(w2).op == Op.PUSH
            send_message(w2, Message(Op.PULL, key=KEY, seq=6, cmd=CMD_F32,
                                     version=1))
            reply = recv_message(w2)
            np.testing.assert_array_equal(
                np.frombuffer(reply.payload, dtype=np.float32), g1 + g2
            )
            close_socket(w1)
            close_socket(w2)
        finally:
            srv.stop()

    def test_fused_members_replay_unfused_bitwise(self):
        """A lost FUSED frame heals by replaying its journaled members as
        plain per-key pushes — the server sums both paths identically."""
        srv = _wire_server(num_workers=2)
        KEY_A, KEY_B, N = 21, 22, 32
        a1 = np.arange(N, dtype=np.float32)
        b1 = np.full(N, 2.0, dtype=np.float32)
        a2 = np.full(N, -1.5, dtype=np.float32)
        b2 = np.arange(N, dtype=np.float32) * 3
        try:
            w1 = connect(srv.host, srv.port)
            w2 = connect(srv.host, srv.port)
            for s in (w1, w2):
                s.settimeout(15)
            for key in (KEY_A, KEY_B):
                _init_key([(w1, 1), (w2, 2)], key, N)
            # worker 2's fused pack (A2+B2) is "lost"; only its journal
            # survives — members recorded individually, fused=True
            journal = RoundJournal(max_rounds=2, max_bytes=1 << 20)
            journal.record(KEY_A, 1, CMD_F32, a2.tobytes(), fused=True)
            journal.record(KEY_B, 1, CMD_F32, b2.tobytes(), fused=True)
            # worker 1 ships ITS round as a fused frame that arrives fine
            frame = encode_fused_push([
                (KEY_A, CMD_F32, 1, a1.tobytes()),
                (KEY_B, CMD_F32, 1, b1.tobytes()),
            ])
            send_message(w1, Message(Op.FUSED, key=KEY_A, seq=1, flags=1,
                                     cmd=2, payload=frame))
            # worker 2 heals: one query covers both keys on this server
            send_message(w2, Message(
                Op.RESYNC_QUERY, key=KEY_A, seq=2, flags=2,
                payload=encode_resync_query(2, [KEY_A, KEY_B]),
            ))
            resp = recv_message(w2)
            assert resp.op == Op.RESYNC_STATE
            state = decode_resync_state(resp.payload)
            seq = 10
            for key in (KEY_A, KEY_B):
                assert state[key]["seen"] == 0
                for e in journal.entries_after(key, 0):
                    assert e.fused
                    send_message(w2, Message(Op.PUSH, key=key, seq=seq,
                                             flags=2, cmd=e.cmd,
                                             version=e.version,
                                             payload=e.payload))
                    assert recv_message(w2).op == Op.PUSH
                    seq += 1
            # both rounds published → worker 1's ONE fused reply carries
            # bitwise the fault-free sums
            from byteps_tpu.comm.transport import decode_fused_reply

            msg = recv_message(w1)
            assert msg.op == Op.FUSED
            sums = {KEY_A: a1 + a2, KEY_B: b1 + b2}
            for key, _ver, payload in decode_fused_reply(msg.payload):
                np.testing.assert_array_equal(
                    np.frombuffer(payload, dtype=np.float32), sums[key]
                )
            close_socket(w1)
            close_socket(w2)
        finally:
            srv.stop()


class TestInitReplayAck:
    """The dropped-init-ACK 2-worker strand (ROADMAP): a retried INIT
    whose barrier already released must be acked from the
    completed-barrier record, not re-parked."""

    def test_post_release_replay_acks_immediately(self):
        srv = _wire_server(num_workers=2)
        KEY, N = 31, 16
        TOK1, TOK2 = 0xA0001, 0xB0001
        base = counters().get("init_replay_ack")
        try:
            w1 = connect(srv.host, srv.port)
            w2 = connect(srv.host, srv.port)
            for s in (w1, w2):
                s.settimeout(15)
            _init_key([(w1, 1), (w2, 2)], KEY, N, tokens=[TOK1, TOK2])
            # worker 1 "lost" its ack: it retries the SAME init (same
            # token).  Pre-fix this re-parked as a waiter and — with
            # worker 2 long released — waited forever.
            send_message(w1, Message(
                Op.INIT, key=KEY, seq=7, flags=1, version=TOK1,
                payload=struct.pack("!QI", N, int(DataType.FLOAT32)),
            ))
            ack = recv_message(w1)  # would raise timeout if parked
            assert ack.op == Op.INIT and ack.seq == 7
            assert counters().get("init_replay_ack") == base + 1
            # the replay-ack must NOT have reset round state: a normal
            # round still completes across both workers, bitwise
            g1 = np.arange(N, dtype=np.float32)
            g2 = np.full(N, 4.0, dtype=np.float32)
            send_message(w1, Message(Op.PUSH, key=KEY, seq=8, flags=1,
                                     cmd=CMD_F32, version=1,
                                     payload=g1.tobytes()))
            send_message(w2, Message(Op.PUSH, key=KEY, seq=9, flags=2,
                                     cmd=CMD_F32, version=1,
                                     payload=g2.tobytes()))
            assert recv_message(w1).op == Op.PUSH
            assert recv_message(w2).op == Op.PUSH
            send_message(w1, Message(Op.PULL, key=KEY, seq=10, cmd=CMD_F32,
                                     version=1))
            reply = recv_message(w1)
            np.testing.assert_array_equal(
                np.frombuffer(reply.payload, dtype=np.float32), g1 + g2
            )
            close_socket(w1)
            close_socket(w2)
        finally:
            srv.stop()

    def test_fresh_token_still_parks(self):
        """A DIFFERENT token (new epoch / restarted client) is a genuine
        new barrier: it must park, not false-ack from the old record."""
        srv = _wire_server(num_workers=2)
        KEY, N = 41, 8
        try:
            w1 = connect(srv.host, srv.port)
            w2 = connect(srv.host, srv.port)
            for s in (w1, w2):
                s.settimeout(15)
            _init_key([(w1, 1), (w2, 2)], KEY, N, tokens=[0xC0001, 0xD0001])
            # worker 1 re-inits with a FRESH token (elastic rejoin shape)
            send_message(w1, Message(
                Op.INIT, key=KEY, seq=20, flags=1, version=0xC0002,
                payload=struct.pack("!QI", N, int(DataType.FLOAT32)),
            ))
            w1.settimeout(1.0)
            with pytest.raises((TimeoutError, socket.timeout, OSError)):
                recv_message(w1)  # parked: barrier waits for worker 2
            # worker 2's matching re-init releases the new barrier
            w1.settimeout(15)
            send_message(w2, Message(
                Op.INIT, key=KEY, seq=21, flags=2, version=0xD0002,
                payload=struct.pack("!QI", N, int(DataType.FLOAT32)),
            ))
            assert recv_message(w1).op == Op.INIT
            assert recv_message(w2).op == Op.INIT
            close_socket(w1)
            close_socket(w2)
        finally:
            srv.stop()


def _reset_chaos_budget():
    from byteps_tpu.comm.chaos import reset_fault_budget

    reset_fault_budget()


class TestHealInPlace:
    """End-to-end: a deterministic one-sided schedule (every PUSH frame
    dropped until the fault budget spends) exhausts the retry budget —
    and the step completes anyway, healed via resync + journal replay,
    with no DegradedError and no re-init barrier."""

    def _cluster_env(self, monkeypatch, sched_port):
        for k, v in {
            "DMLC_PS_ROOT_URI": "127.0.0.1",
            "DMLC_PS_ROOT_PORT": str(sched_port),
            "DMLC_NUM_WORKER": "1",
            "DMLC_NUM_SERVER": "1",
            "BYTEPS_FORCE_DISTRIBUTED": "1",
            "BYTEPS_HEARTBEAT_INTERVAL": "0.2",
            "BYTEPS_RPC_DEADLINE_S": "0.3",
            "BYTEPS_RPC_RETRIES": "2",
            "BYTEPS_RPC_BACKOFF_S": "0.05",
            "BYTEPS_INIT_DEADLINE_S": "1.0",
            "BYTEPS_CONNECT_RETRY_S": "0.2",
        }.items():
            monkeypatch.setenv(k, v)

    @pytest.mark.parametrize(("engine", "stripes"), ENGINE_STRIPES,
                             ids=ENGINE_STRIPES_IDS)
    def test_one_sided_giveup_heals_in_place(self, engine, stripes,
                                             monkeypatch):
        """Runs over BOTH server engines: the C++ data plane answers
        Op.RESYNC_QUERY from its own exactly-once ledger since the
        native-parity port — a give-up against a live native server
        heals in place with no re-init barrier, exactly like the Python
        engine (the ``native`` param id arms the conftest hang guards).
        Native lanes run single-reducer (1) AND striped (4): the healing
        snapshot is now a cross-stripe gather under shard locks."""
        from byteps_tpu.comm.rendezvous import Scheduler

        require_engine(engine)
        set_stripes(monkeypatch, stripes)
        monkeypatch.setenv("BYTEPS_VAN", "chaos:tcp")
        monkeypatch.setenv("BYTEPS_CHAOS_SEED", "5")
        monkeypatch.setenv("BYTEPS_CHAOS_DROP", "1.0")
        monkeypatch.setenv("BYTEPS_CHAOS_OPS", str(int(Op.PUSH)))
        # budget = first attempt + BYTEPS_RPC_RETRIES retries: exactly
        # the one push's budget dies, then the wire is clean — so the
        # heal (query op 23, replay push post-budget) must succeed
        monkeypatch.setenv("BYTEPS_CHAOS_FAULT_BUDGET", "3")
        counters().reset()
        _reset_chaos_budget()
        sched = Scheduler(num_workers=1, num_servers=1, host="127.0.0.1")
        sched.start()
        self._cluster_env(monkeypatch, sched.port)
        srv = make_ps_server(engine, Config.from_env())
        threading.Thread(target=srv.start, daemon=True).start()

        import byteps_tpu as bps

        try:
            bps.init()
            rng = np.random.default_rng(0)
            for step in range(3):
                x = rng.standard_normal(129).astype(np.float32)
                out = bps.push_pull(x, name="resync.heal", average=False)
                # 1 worker ⇒ identity; a double-summed replay returns 2x
                np.testing.assert_array_equal(np.asarray(out), x)
            snap = bps.get_robustness_counters()
            assert snap.get("chaos_drop", 0) == 3, snap
            assert snap.get("resync_attempt", 0) == 1, snap
            # the dropped push was never absorbed: exactly one journaled
            # round replayed, and the re-issued original push deduped
            assert snap.get("resync_replayed_rounds", 0) == 1, snap
            dedupe = "native_push_dedup" if engine == "native" else "push_dedup"
            assert snap.get(dedupe, 0) >= 1, snap
            if engine == "native":
                # the query really was served by the C++ ledger
                assert snap.get("native_resync_query", 0) >= 1, snap
            assert snap.get("resync_giveup", 0) == 0, snap
            # the whole point: the step never failed, nothing re-inited
            assert snap.get("rpc_giveup", 0) == 0, snap
            assert snap.get("degraded_jobs", 0) == 0, snap
        finally:
            bps.shutdown()
            srv.stop()
            sched.stop()
            _reset_chaos_budget()

    def test_api_fallback_heals_when_client_heal_fails(self, monkeypatch):
        """Layer 2: with the client-level heal knocked out, DegradedError
        surfaces and push_pull's degraded-retry wrapper routes through
        engine.heal_degraded — resync + replay + explicit pull — instead
        of the re-init resubmit."""
        from byteps_tpu.comm.ps_client import PSClient
        from byteps_tpu.comm.rendezvous import Scheduler

        monkeypatch.setenv("BYTEPS_VAN", "chaos:tcp")
        monkeypatch.setenv("BYTEPS_CHAOS_SEED", "5")
        monkeypatch.setenv("BYTEPS_CHAOS_DROP", "1.0")
        monkeypatch.setenv("BYTEPS_CHAOS_OPS", str(int(Op.PUSH)))
        monkeypatch.setenv("BYTEPS_CHAOS_FAULT_BUDGET", "3")
        monkeypatch.setenv("BYTEPS_DEGRADED_STEP_RETRIES", "2")
        counters().reset()
        _reset_chaos_budget()
        sched = Scheduler(num_workers=1, num_servers=1, host="127.0.0.1")
        sched.start()
        self._cluster_env(monkeypatch, sched.port)
        srv = PSServer(Config.from_env())
        threading.Thread(target=srv.start, daemon=True).start()

        # first _heal_in_place call (the client-level heal) fails without
        # touching the wire; later calls (engine.heal_degraded's
        # resync_in_place) run for real
        real_heal = PSClient._heal_in_place
        calls = {"n": 0}

        def flaky_heal(self, key, sid):
            calls["n"] += 1
            if calls["n"] == 1:
                return False
            return real_heal(self, key, sid)

        monkeypatch.setattr(PSClient, "_heal_in_place", flaky_heal)

        import byteps_tpu as bps

        try:
            bps.init()
            x = np.arange(200, dtype=np.float32)
            out = bps.push_pull(x, name="resync.fallback", average=False)
            np.testing.assert_array_equal(np.asarray(out), x)
            snap = bps.get_robustness_counters()
            assert calls["n"] >= 2, calls  # both layers exercised
            assert snap.get("rpc_giveup", 0) == 1, snap   # layer 1 failed
            assert snap.get("degraded_jobs", 0) == 1, snap
            assert snap.get("resync_replayed_rounds", 0) == 1, snap
            # in-place: the next submit continues the version sequence
            # (no forced re-init pending)
            from byteps_tpu.core.state import get_state

            assert "resync.fallback" not in get_state().engine._reinit_names
            out2 = bps.push_pull(x + 1, name="resync.fallback", average=False)
            np.testing.assert_array_equal(np.asarray(out2), x + 1)
        finally:
            bps.shutdown()
            srv.stop()
            sched.stop()
            _reset_chaos_budget()

    def test_resync_frames_are_chaos_injectable(self, monkeypatch):
        """BYTEPS_CHAOS_OPS can name the RESYNC ops themselves: the first
        query frame is dropped, and the heal's in-budget re-dial loop
        still lands it — the recovery plane survives its own faults."""
        from byteps_tpu.comm.rendezvous import Scheduler

        monkeypatch.setenv("BYTEPS_VAN", "chaos:tcp")
        monkeypatch.setenv("BYTEPS_CHAOS_SEED", "5")
        monkeypatch.setenv("BYTEPS_CHAOS_DROP", "1.0")
        monkeypatch.setenv(
            "BYTEPS_CHAOS_OPS",
            f"{int(Op.PUSH)},{int(Op.RESYNC_QUERY)}",
        )
        # 3 pushes + the heal's FIRST resync query die; its retry passes
        monkeypatch.setenv("BYTEPS_CHAOS_FAULT_BUDGET", "4")
        counters().reset()
        _reset_chaos_budget()
        sched = Scheduler(num_workers=1, num_servers=1, host="127.0.0.1")
        sched.start()
        self._cluster_env(monkeypatch, sched.port)
        srv = PSServer(Config.from_env())
        threading.Thread(target=srv.start, daemon=True).start()

        import byteps_tpu as bps

        try:
            bps.init()
            x = np.full(64, 2.5, dtype=np.float32)
            out = bps.push_pull(x, name="resync.chaos", average=False)
            np.testing.assert_array_equal(np.asarray(out), x)
            snap = bps.get_robustness_counters()
            assert snap.get("chaos_drop", 0) == 4, snap
            assert snap.get("resync_attempt", 0) == 1, snap
            assert snap.get("resync_giveup", 0) == 0, snap
            assert snap.get("degraded_jobs", 0) == 0, snap
        finally:
            bps.shutdown()
            srv.stop()
            sched.stop()
            _reset_chaos_budget()


def _have_native() -> bool:
    # gate on the PARITY surface, not the pre-parity start symbol: a
    # stale .so (no compiler to rebuild) must SKIP the native lanes, not
    # fail them against an engine that cannot serve FUSED/RESYNC
    return have_native_parity_server()


@pytest.mark.skipif(not _have_native(), reason="native lib not built")
class TestNativeResyncInterop:
    """Native-parity port (replaces the old clean-rejection interop):
    the C++ engine answers RESYNC frames from its own exactly-once
    ledger, acks a replayed post-release INIT from its token record, and
    still rejects genuinely unknown ops cleanly (stream stays framed)."""

    def test_native_server_answers_resync_from_ledger(self, monkeypatch):
        """Wire-level heal against the C++ engine, mirroring the Python
        TestReplayBitwise flow: a worker whose round-1 push was 'lost'
        queries the ledger, sees seen=0, replays from its journal, and
        the peer's parked pull answers with the fault-free sum."""
        from byteps_tpu.server.server import NativePSServer

        monkeypatch.setenv("BYTEPS_VAN", "uds")
        srv = NativePSServer(Config(num_worker=2, num_server=1))
        KEY, N = 11, 64
        g1 = np.arange(N, dtype=np.float32)
        g2 = np.full(N, 0.5, dtype=np.float32)
        try:
            w1 = connect(srv.host, srv.port)
            w2 = connect(srv.host, srv.port)
            for s in (w1, w2):
                s.settimeout(15)
            _init_key([(w1, 1), (w2, 2)], KEY, N)
            journal = RoundJournal(max_rounds=2, max_bytes=1 << 20)
            journal.record(KEY, 1, CMD_F32, g2.tobytes())
            send_message(w1, Message(Op.PUSH, key=KEY, seq=1, flags=1,
                                     cmd=CMD_F32, version=1,
                                     payload=g1.tobytes()))
            assert recv_message(w1).op == Op.PUSH
            send_message(w1, Message(Op.PULL, key=KEY, seq=2, cmd=CMD_F32,
                                     version=1))
            # worker 2 heals: query → the C++ ledger reports seen=0
            send_message(w2, Message(Op.RESYNC_QUERY, key=KEY, seq=3, flags=2,
                                     payload=encode_resync_query(2, [KEY])))
            resp = recv_message(w2)
            assert resp.op == Op.RESYNC_STATE and resp.status == 0
            state = decode_resync_state(resp.payload)
            assert state[KEY]["seen"] == 0
            assert state[KEY]["store_version"] == 0
            for e in journal.entries_after(KEY, state[KEY]["seen"]):
                send_message(w2, Message(Op.PUSH, key=KEY, seq=4, flags=2,
                                         cmd=e.cmd, version=e.version,
                                         payload=e.payload))
                assert recv_message(w2).op == Op.PUSH
            # the round published: worker 1's parked pull answers with
            # EXACTLY the fault-free sum
            reply = recv_message(w1)
            assert reply.op == Op.PULL
            np.testing.assert_array_equal(
                np.frombuffer(reply.payload, dtype=np.float32), g1 + g2
            )
            # replaying AGAIN dedupes (exactly-once): the sum cannot move
            send_message(w2, Message(Op.PUSH, key=KEY, seq=5, flags=2,
                                     cmd=CMD_F32, version=1,
                                     payload=g2.tobytes()))
            assert recv_message(w2).op == Op.PUSH
            send_message(w2, Message(Op.PULL, key=KEY, seq=6, cmd=CMD_F32,
                                     version=1))
            np.testing.assert_array_equal(
                np.frombuffer(recv_message(w2).payload, dtype=np.float32),
                g1 + g2,
            )
            assert srv.native_counters().get("native_push_dedup", 0) >= 1
            assert srv.native_counters().get("native_resync_query", 0) == 1
            close_socket(w1)
            close_socket(w2)
        finally:
            srv.stop()

    def test_native_post_release_init_replay_acked(self, monkeypatch):
        """A replayed INIT (same token) after the barrier released is
        acked from the C++ token record — the dropped-ack strand is
        fixed for BYTEPS_SERVER_NATIVE=1 runs too.  A FRESH token still
        parks (genuine new barrier)."""
        import socket as _socket

        from byteps_tpu.server.server import NativePSServer

        monkeypatch.setenv("BYTEPS_VAN", "uds")
        srv = NativePSServer(Config(num_worker=2, num_server=1))
        KEY, N = 31, 16
        TOK1, TOK2 = 0xA0001, 0xB0001
        try:
            w1 = connect(srv.host, srv.port)
            w2 = connect(srv.host, srv.port)
            for s in (w1, w2):
                s.settimeout(15)
            _init_key([(w1, 1), (w2, 2)], KEY, N, tokens=[TOK1, TOK2])
            # worker 1 "lost" its ack: the SAME-token retry must be acked
            # immediately (pre-port the native engine re-parked it)
            send_message(w1, Message(
                Op.INIT, key=KEY, seq=7, flags=1, version=TOK1,
                payload=struct.pack("!QI", N, int(DataType.FLOAT32)),
            ))
            ack = recv_message(w1)
            assert ack.op == Op.INIT and ack.seq == 7
            assert srv.native_counters().get("native_init_replay_ack") == 1
            # a FRESH token is a genuine new barrier: it parks
            send_message(w1, Message(
                Op.INIT, key=KEY, seq=8, flags=1, version=TOK1 + 1,
                payload=struct.pack("!QI", N, int(DataType.FLOAT32)),
            ))
            w1.settimeout(1.0)
            with pytest.raises((TimeoutError, _socket.timeout, OSError)):
                recv_message(w1)
            w1.settimeout(15)
            send_message(w2, Message(
                Op.INIT, key=KEY, seq=9, flags=2, version=TOK2 + 1,
                payload=struct.pack("!QI", N, int(DataType.FLOAT32)),
            ))
            assert recv_message(w1).op == Op.INIT
            assert recv_message(w2).op == Op.INIT
            close_socket(w1)
            close_socket(w2)
        finally:
            srv.stop()

    def test_native_unknown_op_rejected_cleanly(self, monkeypatch):
        """Ops NEWER than the engine speaks still get the clean nonzero-
        status rejection (op+seq echoed, stream stays framed) — the
        forward-compat contract the old RESYNC rejection exercised."""
        from byteps_tpu.comm.transport import HEADER_FMT, _recv_exact
        from byteps_tpu.server.server import NativePSServer

        monkeypatch.setenv("BYTEPS_VAN", "uds")
        srv = NativePSServer(Config(num_worker=1, num_server=1))
        try:
            sock = connect(srv.host, srv.port)
            sock.settimeout(15)
            send_message(sock, Message(99, key=3, seq=1, payload=b"future"))
            hdr = _recv_exact(sock, struct.calcsize(HEADER_FMT))
            _magic, op, status, _f, seq, _k, _c, _v, length = struct.unpack(
                HEADER_FMT, hdr
            )
            assert (op, seq, length) == (99, 1, 0)
            assert status != 0  # rejected, not swallowed
            # the stream never desynced: a normal round still works
            x = np.arange(8, dtype=np.float32)
            send_message(sock, Message(
                Op.INIT, key=3, seq=2, flags=1,
                payload=struct.pack("!QI", 8, int(DataType.FLOAT32)),
            ))
            assert recv_message(sock).op == Op.INIT
            send_message(sock, Message(Op.PUSH, key=3, seq=3, flags=1,
                                       cmd=CMD_F32, version=1,
                                       payload=x.tobytes()))
            assert recv_message(sock).op == Op.PUSH
            send_message(sock, Message(Op.PULL, key=3, seq=4, cmd=CMD_F32,
                                       version=1))
            reply = recv_message(sock)
            np.testing.assert_array_equal(
                np.frombuffer(reply.payload, dtype=np.float32), x
            )
            close_socket(sock)
        finally:
            srv.stop()


_DEMO_WORKER = r"""
import json, os, sys
import numpy as np
import byteps_tpu as bps

bps.init()
rank = bps.rank()
N = 64
for step in range(3):
    g = (np.arange(N, dtype=np.float32) + step) * (rank + 1)
    out = np.asarray(bps.push_pull(g, name="demo.g", average=False))
    base = np.arange(N, dtype=np.float32) + step
    np.testing.assert_array_equal(out, base * 1 + base * 2)
print("COUNTERS=" + json.dumps(bps.get_robustness_counters()))
print("DEMO_OK rank=%d" % rank)
"""


class TestTwoWorkerDemo:
    """The acceptance demo (mirrors docs/robustness.md): 2 workers + 1
    server under a seeded schedule that kills ONE worker's push retry
    budget mid-run.  The victim heals in place via resync; its peer
    never blocks or re-inits; every pulled tensor on BOTH workers is
    bitwise identical to the fault-free run."""

    @pytest.mark.parametrize("engine", ["python", "native"])
    def test_victim_heals_in_place_peer_never_blocks(self, engine,
                                                     monkeypatch):
        from byteps_tpu.comm.rendezvous import Scheduler

        require_engine(engine)
        # parent (scheduler + server): chaos van selected but ZERO fault
        # probabilities — response lanes stay clean; each worker
        # subprocess brings its own fault env.  Under ``native`` the
        # victim heals against the LIVE C++ engine's ledger while its
        # peer keeps pulling — the acceptance shape for the parity port.
        monkeypatch.setenv("BYTEPS_VAN", "chaos:tcp")
        monkeypatch.setenv("BYTEPS_HEARTBEAT_INTERVAL", "0.2")
        monkeypatch.setenv("DMLC_NUM_WORKER", "2")
        monkeypatch.setenv("DMLC_NUM_SERVER", "1")
        sched = Scheduler(num_workers=2, num_servers=1, host="127.0.0.1")
        sched.start()
        monkeypatch.setenv("DMLC_PS_ROOT_URI", "127.0.0.1")
        monkeypatch.setenv("DMLC_PS_ROOT_PORT", str(sched.port))
        srv = make_ps_server(engine, Config.from_env())
        threading.Thread(target=srv.start, daemon=True).start()

        base_env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "DMLC_PS_ROOT_URI": "127.0.0.1",
            "DMLC_PS_ROOT_PORT": str(sched.port),
            "DMLC_NUM_WORKER": "2",
            "DMLC_NUM_SERVER": "1",
            "BYTEPS_HEARTBEAT_INTERVAL": "0.5",
        }
        victim_env = {
            **base_env,
            "DMLC_WORKER_ID": "0",
            "BYTEPS_NODE_UID": "demo-victim",
            # deterministic one-sided kill: exactly the first 3 PUSH
            # frames (attempt + 2 retries) die, then the wire is clean
            "BYTEPS_CHAOS_SEED": "9",
            "BYTEPS_CHAOS_DROP": "1.0",
            "BYTEPS_CHAOS_OPS": str(int(Op.PUSH)),
            "BYTEPS_CHAOS_FAULT_BUDGET": "3",
            "BYTEPS_RPC_DEADLINE_S": "0.3",
            "BYTEPS_RPC_RETRIES": "2",
            "BYTEPS_RPC_BACKOFF_S": "0.05",
        }
        peer_env = {
            **base_env,
            "DMLC_WORKER_ID": "1",
            "BYTEPS_NODE_UID": "demo-peer",
        }
        try:
            procs = [
                subprocess.Popen(
                    [sys.executable, "-c", _DEMO_WORKER],
                    env=env, stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT, text=True,
                )
                for env in (victim_env, peer_env)
            ]
            outs = []
            deadline = time.monotonic() + 120
            for p in procs:
                try:
                    out, _ = p.communicate(
                        timeout=max(5.0, deadline - time.monotonic())
                    )
                except subprocess.TimeoutExpired:
                    p.kill()
                    out, _ = p.communicate()
                    pytest.fail(f"demo worker hung:\n{out}")
                outs.append(out)
            for p, out in zip(procs, outs):
                assert p.returncode == 0, f"worker failed:\n{out}"
                assert "DEMO_OK" in out, out
            victim_out = outs[0]
            snap = json.loads(
                victim_out.split("COUNTERS=", 1)[1].splitlines()[0]
            )
            # the victim really exhausted its budget and healed in place
            assert snap.get("chaos_drop", 0) == 3, snap
            assert snap.get("resync_attempt", 0) >= 1, snap
            assert snap.get("resync_giveup", 0) == 0, snap
            assert snap.get("degraded_jobs", 0) == 0, snap
        finally:
            srv.stop()
            sched.stop()
