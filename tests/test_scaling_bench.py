"""Scaling-efficiency harness machinery test (BASELINE.md north-star
metric exists and measures something sane even on shared-CPU loopback)."""

import json
import subprocess
import sys

import pytest


class TestScalingHarness:
    def test_harness_runs_and_reports(self):
        out = subprocess.run(
            [sys.executable, "tools/scaling_bench.py",
             "--workers", "1,2", "--mbytes", "0.5", "--rounds", "3",
             "--keys", "8"],
            capture_output=True, text=True, timeout=240, cwd="/root/repo",
        )
        assert out.returncode == 0, out.stdout + out.stderr
        line = out.stdout.strip().splitlines()[-1]
        rec = json.loads(line)
        assert rec["metric"] == "pushpull_throughput_retention_2w"
        assert rec["unit"] == "ratio"
        assert 0.1 < rec["value"] < 3.0
        assert "round_time_s" in rec["extra"]
        assert rec["extra"]["round_time_s"]["1"] > 0

    def test_committed_r05_artifact_meets_verdict_bars(self):
        """SCALING_r05.json (built by tools/run_scaling_r05.sh +
        assemble_scaling_r05.py on a quiet box) must carry the VERDICT r4
        #6 done-criteria: native-shm ≥ native-tcp in absolute MB/s at
        every N, and 8-worker retention ≥ 0.5."""
        import os

        path = os.path.join(os.path.dirname(__file__), "..", "SCALING_r05.json")
        assert os.path.exists(path), "SCALING_r05.json not committed"
        d = json.load(open(path))
        cells = {c["label"]: c for c in d["configs"]}
        for topo in ("scaledsrv", "2srv"):
            shm = cells[f"native-shm-{topo}"]["aggregate_mb_per_s"]
            tcp = cells[f"native-tcp-{topo}"]["aggregate_mb_per_s"]
            for n in ("1", "2", "4", "8"):
                assert shm[n] >= tcp[n], (topo, n, shm[n], tcp[n])
        assert d["headline"]["retention_8w"] >= 0.5
        assert cells["native-shm-scaledsrv"]["retention_vs_1w"]["8"] >= 0.5
