"""Scaling-efficiency harness machinery test (BASELINE.md north-star
metric exists and measures something sane even on shared-CPU loopback)."""

import json
import subprocess
import sys

import pytest


class TestScalingHarness:
    def test_harness_runs_and_reports(self):
        out = subprocess.run(
            [sys.executable, "tools/scaling_bench.py",
             "--workers", "1,2", "--mbytes", "0.5", "--rounds", "3",
             "--keys", "8"],
            capture_output=True, text=True, timeout=240, cwd="/root/repo",
        )
        assert out.returncode == 0, out.stdout + out.stderr
        line = out.stdout.strip().splitlines()[-1]
        rec = json.loads(line)
        assert rec["metric"] == "pushpull_throughput_retention_2w"
        assert rec["unit"] == "ratio"
        assert 0.1 < rec["value"] < 3.0
        assert "round_time_s" in rec["extra"]
        assert rec["extra"]["round_time_s"]["1"] > 0
