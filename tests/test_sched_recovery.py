"""Scheduler crash recovery: restartable control plane with incarnation
fencing and live rejoin (docs/robustness.md "Control-plane recovery").

The scheduler used to be the job's single point of failure: one
``kill -9`` and every node latched ``_sched_dead``, the heartbeat loop
exited permanently, and the cluster could never resize, evict, reshard,
or aggregate metrics again — even though the worker↔server data plane
was perfectly healthy.  These tests pin the recovery contract:

- scheduler-link loss puts a node in ``control_plane_degraded`` mode
  (data plane keeps training on the last-adopted book) while a
  reconnect state machine redials with bounded backoff;
- a restarted scheduler mints a new incarnation, rebuilds its
  registration table from the survivors' re-REGISTERs (uid + last-known
  rank + epochs), and fences its first books strictly ABOVE every
  reported epoch;
- nodes refuse books from an older incarnation (zombie scheduler);
- pending barriers re-arm across the restart instead of stranding;
- the first heartbeat to a new incarnation ships the FULL metric
  history, not a delta against baselines the dead scheduler took with
  it;
- scheduler-link faults are deterministically injectable
  (``BYTEPS_CHAOS_SCHED`` + ``BYTEPS_CHAOS_OPS=PING/ADDRBOOK`` +
  ``BYTEPS_CHAOS_TARGET_PORT``).
"""

import json
import os
import socket
import threading
import time

import numpy as np
import pytest

from byteps_tpu.common.config import Config
from byteps_tpu.comm.rendezvous import GROUP_WORKERS, Scheduler
from byteps_tpu.comm.transport import Message, Op, recv_message, send_message
from byteps_tpu.core.telemetry import counters


def _set_env(env: dict) -> dict:
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    return old


def _restore_env(old: dict) -> None:
    for k, v in old.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


#: fast-recovery knobs shared by the e2e tests below
_FAST = {
    "DMLC_PS_ROOT_URI": "127.0.0.1",
    "BYTEPS_FORCE_DISTRIBUTED": "1",
    "BYTEPS_HEARTBEAT_INTERVAL": "0.1",
    "BYTEPS_SCHED_RECONNECT_RETRIES": "80",
    "BYTEPS_SCHED_RECONNECT_BACKOFF_S": "0.05",
    "BYTEPS_SCHED_REJOIN_WINDOW_S": "5",
    "BYTEPS_CONNECT_RETRY_S": "0.2",
}


def _roundtrip(client, key, value, version, n=64):
    done = threading.Event()
    box = []
    payload = np.full(n, value, np.float32).tobytes()
    client.push(key, payload, 0, version, cb=lambda: done.set())
    assert done.wait(10)
    got = threading.Event()
    client.pull(key, version, lambda p: (box.append(p), got.set()))
    assert got.wait(10)
    return np.frombuffer(box[0], np.float32)


def _register_raw(port: int, payload: dict, timeout: float = 5.0):
    """One raw-socket REGISTER → (socket, reply Message).  The caller
    owns the socket (keep it open: closing tells the scheduler the node
    died)."""
    sock = socket.create_connection(("127.0.0.1", port), timeout=timeout)
    sock.settimeout(timeout)
    send_message(sock, Message(Op.REGISTER, payload=json.dumps(payload).encode()))
    return sock, recv_message(sock)


class TestIncarnationFence:
    def test_client_refuses_older_incarnation_book(self):
        from byteps_tpu.comm.ps_client import PSClient

        pc = PSClient.__new__(PSClient)
        pc.sched_incarnation = 0
        counters().reset()
        assert pc._fence_book({"sched_incarnation": 5})
        assert pc.sched_incarnation == 5
        # zombie scheduler racing its successor: older incarnation refused
        assert not pc._fence_book({"sched_incarnation": 3})
        assert pc.sched_incarnation == 5
        assert counters().get("sched_stale_book") == 1
        # same incarnation and unstamped (legacy) books pass
        assert pc._fence_book({"sched_incarnation": 5})
        assert pc._fence_book({})

    def test_server_refuses_older_incarnation_book(self):
        from byteps_tpu.server.server import PSServer

        srv = PSServer.__new__(PSServer)
        srv.sched_incarnation = 0
        counters().reset()
        assert srv._fence_book({"sched_incarnation": 9})
        assert srv.sched_incarnation == 9
        assert not srv._fence_book({"sched_incarnation": 8})
        assert counters().get("sched_stale_book") == 1
        assert srv._fence_book({"sched_incarnation": 10})
        assert srv.sched_incarnation == 10

    def test_resize_book_from_zombie_is_not_applied(self):
        """A stale-incarnation RESIZE book on the control connection is
        dropped BEFORE any topology field is adopted."""
        from byteps_tpu.server.server import PSServer

        srv = PSServer.__new__(PSServer)
        srv.sched_incarnation = 7
        srv.membership_epoch = 4
        srv._map_epoch = 0
        srv.num_workers = 2
        calls = []
        srv.update_num_workers = lambda n: calls.append(n)
        book = {"sched_incarnation": 6, "num_workers": 99, "epoch": 9,
                "worker_ranks": [0]}
        from byteps_tpu.comm.rendezvous import RESIZE_SEQ

        msg = Message(Op.ADDRBOOK, seq=RESIZE_SEQ,
                      payload=json.dumps(book).encode())
        srv._handle_control(None, msg)
        assert calls == [] and srv.num_workers == 2
        assert srv.membership_epoch == 4  # stale book noted nothing


class TestRestartedSchedulerFencesEpochs:
    def test_first_book_fences_above_reported_epochs_and_honors_rank(self):
        """A reborn scheduler must never emit a map epoch <= one any
        rejoining node reported, and must give a rejoiner its last-known
        rank back (ledgers, key placement, and barrier sizing all key on
        rank identity)."""
        sched = Scheduler(num_workers=2, num_servers=0, host="127.0.0.1",
                          rejoin_window=30.0)
        sched.start()
        try:
            s0, _b = None, None
            # rejoiner reporting rank 1 and epochs it acted under
            s1 = socket.create_connection(("127.0.0.1", sched.port), timeout=5)
            s1.settimeout(10)
            send_message(s1, Message(Op.REGISTER, payload=json.dumps({
                "role": "worker", "host": "", "port": 0, "uid": "fence-w1",
                "num_workers": 2, "num_servers": 0,
                "last_rank": 1, "epoch": 3, "map_epoch": 7,
            }).encode()))
            # second rejoiner completes the population → books emit
            s0, resp0 = _register_raw(sched.port, {
                "role": "worker", "host": "", "port": 0, "uid": "fence-w0",
                "num_workers": 2, "num_servers": 0,
                "last_rank": 0, "epoch": 3, "map_epoch": 7,
            }, timeout=10)
            book0 = json.loads(resp0.payload.decode())
            book1 = json.loads(recv_message(s1).payload.decode())
            assert book1["rank"] == 1 and book0["rank"] == 0
            assert book0["map_epoch"] > 7, book0
            assert book0["epoch"] > 3, book0
            assert book0["sched_incarnation"] == sched.incarnation
            assert book0["is_recovery"] is True
            assert sched.map_epoch > 7
            s0.close()
            s1.close()
        finally:
            sched.stop()


class TestRebornTunerReadoption:
    def test_first_book_confirms_survivor_tuning(self, monkeypatch):
        """A reborn scheduler's tuner re-adopts the fleet's live tuning
        (fusion threshold + ring overrides) from the survivors' rejoin
        reports BEFORE emitting its first books — the book confirms the
        running decisions instead of reverting them and migrating every
        overridden key home (docs/autotune.md)."""
        monkeypatch.setenv("BYTEPS_AUTOTUNE", "1")
        sched = Scheduler(num_workers=2, num_servers=0, host="127.0.0.1",
                          rejoin_window=30.0)
        sched.start()
        try:
            report = {
                "epoch": 5, "fusion_threshold": 131072,
                "codec_off": ["topk"],
                "ring_overrides": {"65536": 1},
            }
            s1 = socket.create_connection(("127.0.0.1", sched.port),
                                          timeout=5)
            s1.settimeout(10)
            send_message(s1, Message(Op.REGISTER, payload=json.dumps({
                "role": "worker", "host": "", "port": 0, "uid": "tun-w1",
                "num_workers": 2, "num_servers": 0,
                "last_rank": 1, "epoch": 3, "map_epoch": 7,
                "tuning": report,
            }).encode()))
            s0, resp0 = _register_raw(sched.port, {
                "role": "worker", "host": "", "port": 0, "uid": "tun-w0",
                "num_workers": 2, "num_servers": 0,
                "last_rank": 0, "epoch": 3, "map_epoch": 7,
                # stale report from a slower adopter: monotone by
                # tuning epoch, the newest report wins
                "tuning": {"epoch": 2, "fusion_threshold": 4096},
            }, timeout=10)
            book0 = json.loads(resp0.payload.decode())
            recv_message(s1)  # drain w1's book
            assert book0["tuning"]["epoch"] == 5
            assert book0["tuning"]["fusion_threshold"] == 131072
            assert book0["tuning"]["codec_off"] == ["topk"]
            # state carries the override (the BOOK filters it to live
            # server ranks — none registered here)
            assert sched.tuner.state.overrides == {65536: 1}
            s0.close()
            s1.close()
        finally:
            sched.stop()

    def test_live_scheduler_ignores_rejoin_tuning(self, monkeypatch):
        """Once books are out, the scheduler's own tuner state is
        authoritative — a late rejoiner's report (necessarily from an
        older incarnation or a stale window) must not perturb it."""
        monkeypatch.setenv("BYTEPS_AUTOTUNE", "1")
        sched = Scheduler(num_workers=1, num_servers=0, host="127.0.0.1",
                          rejoin_window=30.0)
        sched.start()
        try:
            s0, _ = _register_raw(sched.port, {
                "role": "worker", "host": "", "port": 0, "uid": "live-w0",
                "num_workers": 1, "num_servers": 0,
            }, timeout=10)
            epoch0 = sched.tuner.state.epoch
            s0.close()
            time.sleep(0.1)
            s1, resp = _register_raw(sched.port, {
                "role": "worker", "host": "", "port": 0, "uid": "live-w0",
                "num_workers": 1, "num_servers": 0,
                "last_rank": 0, "epoch": 1, "map_epoch": 1,
                "tuning": {"epoch": 50, "fusion_threshold": 4096},
            }, timeout=10)
            assert resp.status == 0
            assert sched.tuner.state.epoch == epoch0
            assert sched.tuner.state.fusion_threshold is None
            s1.close()
        finally:
            sched.stop()


class TestSchedulerRestartRejoin:
    def test_crash_restart_full_rejoin_traffic_bitwise(self):
        """The acceptance e2e: SIGKILL-equivalent scheduler crash +
        restart on the same address.  The data plane trains bitwise
        THROUGH the outage, every node rejoins the new incarnation with
        zero evictions and stable ranks, heartbeats resume, and the
        rebuilt cluster aggregate holds the FULL metric history."""
        from byteps_tpu.comm.ps_client import PSClient
        from byteps_tpu.server.server import PSServer

        old = _set_env({**_FAST, "DMLC_NUM_WORKER": "1", "DMLC_NUM_SERVER": "1"})
        counters().reset()
        sched = Scheduler(1, 1, host="127.0.0.1")
        sched.start()
        os.environ["DMLC_PS_ROOT_PORT"] = str(sched.port)
        old.setdefault("DMLC_PS_ROOT_PORT", None)
        sched2 = None
        try:
            cfg = Config.from_env()
            srv = PSServer(cfg)
            threading.Thread(target=srv.start, daemon=True).start()
            w = PSClient(cfg, node_uid="rej-w0")
            w.connect()
            w.init_tensor(5, 64, 0)
            np.testing.assert_array_equal(_roundtrip(w, 5, 1.5, 1), 1.5)

            inc0, map0, port = sched.incarnation, sched.map_epoch, sched.port
            sched.crash()
            time.sleep(0.3)
            # degraded-mode survival: the data plane must not notice
            np.testing.assert_array_equal(_roundtrip(w, 5, 2.5, 2), 2.5)
            assert w._sched_dead  # control plane really was down

            sched2 = Scheduler(1, 1, host="127.0.0.1", port=port)
            sched2.start()
            deadline = time.time() + 20
            while time.time() < deadline:
                if (w.sched_incarnation > inc0 and not w._sched_dead
                        and sched2._addrbook_sent):
                    break
                time.sleep(0.1)
            assert w.sched_incarnation > inc0, "worker never rejoined"
            assert sched2._addrbook_sent, "membership not re-established"
            assert sched2.map_epoch > map0, "map epoch not fenced"
            assert sched2.eviction_totals == {"worker": 0, "server": 0}, (
                "spurious eviction at rebirth"
            )
            assert w.rank == 0 and srv.rank == 0  # rank-stable rebirth
            np.testing.assert_array_equal(_roundtrip(w, 5, 3.5, 3), 3.5)

            # heartbeats resumed against the new incarnation
            deadline = time.time() + 10
            while time.time() < deadline:
                live = w.query_cluster()
                if 0 in live["worker"] and 0 in live["server"]:
                    break
                time.sleep(0.1)
            assert 0 in live["worker"] and 0 in live["server"]
            snap = counters().snapshot()
            assert snap.get("sched_rejoin", 0) >= 2, snap  # worker + server

            # metrics continuity: first beats to the new incarnation
            # shipped FULL snapshots, so the rebuilt aggregate equals
            # the local totals (not just the post-restart delta)
            deadline = time.time() + 10
            while time.time() < deadline:
                agg = sched2.metrics_agg.counters.snapshot()
                if agg.get("wire_rpc", 0) == counters().get("wire_rpc"):
                    break
                time.sleep(0.2)
            assert agg.get("wire_rpc", 0) == counters().get("wire_rpc"), (
                "rebuilt aggregate is missing pre-crash history"
            )
            w.close()
            srv.stop()
        finally:
            _restore_env(old)
            sched.stop()
            if sched2 is not None:
                sched2.stop()


class TestBarrierRearmAcrossRestart:
    def test_pending_barrier_rearms_from_reregistration(self):
        """A worker parked in a scheduler barrier when the scheduler
        dies must NOT strand: its barrier call rides the reconnect
        machine, re-sends against the restarted scheduler's empty
        barrier table, and pairs with its peer's re-sent barrier."""
        from byteps_tpu.comm.ps_client import PSClient

        old = _set_env({**_FAST, "DMLC_NUM_WORKER": "2", "DMLC_NUM_SERVER": "0"})
        sched = Scheduler(2, 0, host="127.0.0.1")
        sched.start()
        os.environ["DMLC_PS_ROOT_PORT"] = str(sched.port)
        old.setdefault("DMLC_PS_ROOT_PORT", None)
        sched2 = None
        try:
            cfg = Config.from_env()
            w0 = PSClient(cfg, node_uid="bar-w0")
            w1 = PSClient(cfg, node_uid="bar-w1")
            t0 = threading.Thread(target=w0.connect, daemon=True)
            t0.start()
            w1.connect()
            t0.join(10)
            assert w0.rank is not None and w1.rank is not None

            done = [threading.Event(), threading.Event()]

            def bar(i, w):
                w.barrier(GROUP_WORKERS)
                done[i].set()

            threading.Thread(target=bar, args=(0, w0), daemon=True).start()
            time.sleep(0.4)  # w0's waiter is parked at the scheduler
            assert not done[0].is_set()
            port = sched.port
            sched.crash()
            time.sleep(0.2)
            sched2 = Scheduler(2, 0, host="127.0.0.1", port=port)
            sched2.start()
            # peer re-sends its barrier after rejoining; both must pair
            threading.Thread(target=bar, args=(1, w1), daemon=True).start()
            assert done[0].wait(20), "parked barrier stranded across restart"
            assert done[1].wait(20), "peer barrier stranded across restart"
            w0.close()
            w1.close()
        finally:
            _restore_env(old)
            sched.stop()
            if sched2 is not None:
                sched2.stop()


class TestReconnectDoesNotArmBarrierBypass:
    def test_next_barrier_pairs_after_reconnect_rejoin(self):
        """A control-plane RECONNECT (link hiccup, scheduler alive) must
        NOT mark the conn recovered: the client never tears its runtime
        down and runs no re-init barrier to consume the bypass, so the
        node's next TRAINING barrier would release unpaired and desync
        it from its peers (review finding)."""
        from byteps_tpu.comm.ps_client import PSClient
        from byteps_tpu.comm.transport import close_socket

        old = _set_env({**_FAST, "DMLC_NUM_WORKER": "2", "DMLC_NUM_SERVER": "0"})
        counters().reset()
        sched = Scheduler(2, 0, host="127.0.0.1")
        sched.start()
        os.environ["DMLC_PS_ROOT_PORT"] = str(sched.port)
        old.setdefault("DMLC_PS_ROOT_PORT", None)
        try:
            cfg = Config.from_env()
            w0 = PSClient(cfg, node_uid="byp-w0")
            w1 = PSClient(cfg, node_uid="byp-w1")
            t0 = threading.Thread(target=w0.connect, daemon=True)
            t0.start()
            w1.connect()
            t0.join(10)
            # hiccup w0's control link; the scheduler stays up
            close_socket(w0._sched)
            deadline = time.time() + 15
            while time.time() < deadline:
                if counters().get("sched_rejoin") >= 1:
                    break
                time.sleep(0.05)
            assert counters().get("sched_rejoin") >= 1

            done = [threading.Event(), threading.Event()]
            threading.Thread(
                target=lambda: (w0.barrier(GROUP_WORKERS), done[0].set()),
                daemon=True,
            ).start()
            # the rejoined conn must WAIT for its peer, not bypass
            assert not done[0].wait(1.0), (
                "reconnect rejoin armed the barrier bypass: barrier "
                "released without the peer"
            )
            threading.Thread(
                target=lambda: (w1.barrier(GROUP_WORKERS), done[1].set()),
                daemon=True,
            ).start()
            assert done[0].wait(10) and done[1].wait(10)
            w0.close()
            w1.close()
        finally:
            _restore_env(old)
            sched.stop()


class TestReconnectScrubsStaleBarrierWaiter:
    def test_parked_barrier_does_not_double_count_after_reconnect(self):
        """A worker whose control link dies WHILE its barrier is parked
        re-sends the barrier after rejoining; the scheduler must scrub
        the dead connection's stale waiter at re-register — otherwise
        the same rank counts twice and the barrier releases without its
        peer (review finding)."""
        from byteps_tpu.comm.ps_client import PSClient
        from byteps_tpu.comm.transport import close_socket

        old = _set_env({**_FAST, "DMLC_NUM_WORKER": "2", "DMLC_NUM_SERVER": "0"})
        counters().reset()
        sched = Scheduler(2, 0, host="127.0.0.1")
        sched.start()
        os.environ["DMLC_PS_ROOT_PORT"] = str(sched.port)
        old.setdefault("DMLC_PS_ROOT_PORT", None)
        try:
            cfg = Config.from_env()
            w0 = PSClient(cfg, node_uid="scrub-w0")
            w1 = PSClient(cfg, node_uid="scrub-w1")
            t0 = threading.Thread(target=w0.connect, daemon=True)
            t0.start()
            w1.connect()
            t0.join(10)
            done = [threading.Event(), threading.Event()]
            threading.Thread(
                target=lambda: (w0.barrier(GROUP_WORKERS), done[0].set()),
                daemon=True,
            ).start()
            time.sleep(0.4)  # w0's waiter is parked at the scheduler
            close_socket(w0._sched)  # link dies UNDER the parked barrier
            deadline = time.time() + 15
            while time.time() < deadline:
                if counters().get("sched_rejoin") >= 1:
                    break
                time.sleep(0.05)
            assert counters().get("sched_rejoin") >= 1
            # w0's retry re-sent its barrier — it must NOT release on the
            # stale waiter + retry double-count; w1 never arrived
            assert not done[0].wait(1.0), (
                "stale barrier waiter double-counted the reconnected rank"
            )
            threading.Thread(
                target=lambda: (w1.barrier(GROUP_WORKERS), done[1].set()),
                daemon=True,
            ).start()
            assert done[0].wait(10) and done[1].wait(10)
            w0.close()
            w1.close()
        finally:
            _restore_env(old)
            sched.stop()


class TestRebirthWindowDuplicateRegister:
    def test_same_uid_reregister_during_fill_replaces_not_appends(self):
        """A rejoiner whose parked reply's conn dies redials and
        re-REGISTERs the same uid while the rebirth window is still
        filling — the entry must be REPLACED: a ghost append would steal
        the node's own rank hint, inflate the population count, and
        burn one of the first books on a dead socket (review finding)."""
        sched = Scheduler(num_workers=2, num_servers=0, host="127.0.0.1",
                          rejoin_window=30.0)
        sched.start()
        try:
            payload1 = {"role": "worker", "host": "", "port": 0,
                        "uid": "dup-w1", "num_workers": 2,
                        "num_servers": 0, "last_rank": 1, "epoch": 1,
                        "map_epoch": 1}
            s1 = socket.create_connection(("127.0.0.1", sched.port), timeout=5)
            send_message(s1, Message(
                Op.REGISTER, payload=json.dumps(payload1).encode()
            ))
            time.sleep(0.3)  # parked (population 1/2); now the conn dies
            s1.close()
            s2 = socket.create_connection(("127.0.0.1", sched.port), timeout=5)
            s2.settimeout(10)
            send_message(s2, Message(
                Op.REGISTER, payload=json.dumps(payload1).encode()
            ))
            time.sleep(0.3)
            with sched._lock:
                n_workers = len(sched._nodes["worker"])
            assert n_workers == 1, (
                f"duplicate uid created a ghost entry ({n_workers} nodes)"
            )
            # the peer completes the population → books emit correctly
            s0, resp0 = _register_raw(sched.port, {
                "role": "worker", "host": "", "port": 0, "uid": "dup-w0",
                "num_workers": 2, "num_servers": 0, "last_rank": 0,
                "epoch": 1, "map_epoch": 1,
            }, timeout=10)
            book1 = json.loads(recv_message(s2).payload.decode())
            book0 = json.loads(resp0.payload.decode())
            assert book1["rank"] == 1 and book0["rank"] == 0
            assert book0["num_workers"] == 2
            s0.close()
            s2.close()
        finally:
            sched.stop()


class TestHeartbeatSurvivesHiccup:
    def test_transient_link_loss_hands_off_to_reconnect(self):
        """Satellite fix: a single scheduler-link failure used to
        silently end ALL future beats and metric deltas for the node
        (the heartbeat loop's permanent ``return``).  Now it hands off
        to the reconnect machine, re-registers against the SAME live
        scheduler, and keeps beating."""
        from byteps_tpu.comm.ps_client import PSClient
        from byteps_tpu.comm.transport import close_socket
        from byteps_tpu.server.server import PSServer

        old = _set_env({**_FAST, "DMLC_NUM_WORKER": "1", "DMLC_NUM_SERVER": "1"})
        counters().reset()
        sched = Scheduler(1, 1, host="127.0.0.1")
        sched.start()
        os.environ["DMLC_PS_ROOT_PORT"] = str(sched.port)
        old.setdefault("DMLC_PS_ROOT_PORT", None)
        try:
            cfg = Config.from_env()
            srv = PSServer(cfg)
            threading.Thread(target=srv.start, daemon=True).start()
            w = PSClient(cfg, node_uid="hic-w0")
            w.connect()
            inc0 = w.sched_incarnation
            # transient hiccup: the link dies under the node, scheduler
            # stays up
            close_socket(w._sched)
            # wait for the REJOIN itself (polling _sched_dead alone races
            # the recv loop, which may not have noticed the close yet)
            deadline = time.time() + 15
            while time.time() < deadline:
                if counters().get("sched_rejoin") >= 1:
                    break
                time.sleep(0.05)
            assert counters().get("sched_rejoin") >= 1, (
                "reconnect machine never rejoined"
            )
            deadline = time.time() + 5
            while time.time() < deadline:
                with w._sched_cb_lock:
                    if not w._sched_dead:
                        break
                time.sleep(0.05)
            with w._sched_cb_lock:
                assert not w._sched_dead
            assert w.sched_incarnation == inc0  # same scheduler, same life
            assert w.rank == 0
            # beats flow again: the scheduler's liveness stamp refreshes
            deadline = time.time() + 10
            while time.time() < deadline:
                live = w.query_cluster()
                if live["worker"].get(0, 99) < 1.0:
                    break
                time.sleep(0.1)
            assert live["worker"].get(0, 99) < 1.0, (
                "heartbeats did not resume after the hiccup"
            )
            w.close()
            srv.stop()
        finally:
            _restore_env(old)
            sched.stop()


class TestMetricsReship:
    def test_reship_for_rebases_once_per_token(self):
        from byteps_tpu.core.telemetry import MetricsRegistry

        reg = MetricsRegistry()
        reg.counters.bump("rpc_retry", 3)
        d1 = reg.delta_snapshot()
        assert d1["c"]["rpc_retry"] == 3
        reg.counters.bump("rpc_retry", 2)
        # new consumer: full history ships, not the 2-delta
        assert reg.reship_for(111) is True
        d2 = reg.delta_snapshot()
        assert d2["c"]["rpc_retry"] == 5
        # idempotent per token: a second beat loop sharing this registry
        # must NOT re-ship what the first already delivered
        assert reg.reship_for(111) is False
        reg.counters.bump("rpc_retry", 1)
        assert reg.delta_snapshot()["c"]["rpc_retry"] == 1  # deltas resume

    def test_reship_reregisters_gauges_and_drops_requeued(self):
        from byteps_tpu.core.telemetry import MetricsRegistry

        reg = MetricsRegistry()
        reg.gauge_set("control_plane_degraded", 0)
        d = reg.delta_snapshot()
        assert any(g["n"] == "control_plane_degraded" for g in d.get("g", []))
        assert not reg.delta_snapshot().get("g")  # unchanged → not re-sent
        # a failed-send delta parked for requeue is SUBSUMED by the full
        # re-ship (keeping it would double-count in the new aggregate)
        reg.counters.bump("rpc_retry", 4)
        lost = reg.delta_snapshot()
        reg.requeue_delta(lost)
        reg.reship_for(222)
        d = reg.delta_snapshot()
        assert d["c"]["rpc_retry"] == 4  # full totals, counted ONCE
        assert any(g["n"] == "control_plane_degraded" for g in d.get("g", []))


class TestChaosSchedulerLink:
    def test_dropped_ping_costs_one_beat_not_the_loop(self, monkeypatch):
        """BYTEPS_CHAOS_SCHED + BYTEPS_CHAOS_OPS=PING +
        BYTEPS_CHAOS_TARGET_PORT=<scheduler> drops exactly the first
        budgeted heartbeat frames on an otherwise healthy link; the
        beat loop must absorb them (bounded request wait + requeue) and
        keep beating once the budget is spent."""
        from byteps_tpu.comm.chaos import reset_fault_budget
        from byteps_tpu.comm.ps_client import PSClient
        from byteps_tpu.server.server import PSServer

        sched_env = {
            **_FAST,
            "DMLC_NUM_WORKER": "1", "DMLC_NUM_SERVER": "1",
            "BYTEPS_VAN": "chaos:tcp",
            "BYTEPS_CHAOS_SCHED": "1",
            "BYTEPS_CHAOS_OPS": "PING",
            "BYTEPS_CHAOS_DROP": "1.0",
            "BYTEPS_CHAOS_SEED": "5",
            "BYTEPS_HEARTBEAT_INTERVAL": "0.2",
        }
        old = _set_env(sched_env)
        counters().reset()
        sched = Scheduler(1, 1, host="127.0.0.1")
        sched.start()
        os.environ["DMLC_PS_ROOT_PORT"] = str(sched.port)
        old.setdefault("DMLC_PS_ROOT_PORT", None)
        os.environ["BYTEPS_CHAOS_TARGET_PORT"] = str(sched.port)
        old.setdefault("BYTEPS_CHAOS_TARGET_PORT", None)
        reset_fault_budget(2)
        try:
            cfg = Config.from_env()
            srv = PSServer(cfg)
            threading.Thread(target=srv.start, daemon=True).start()
            w = PSClient(cfg, node_uid="chaos-hb-w0")
            w.connect()
            # the first budgeted PINGs die; once spent, beats land and
            # the scheduler's worker stamp goes fresh again
            deadline = time.time() + 30
            fresh = False
            while time.time() < deadline:
                if counters().get("chaos_drop") >= 2:
                    live = sched.liveness()
                    if live["worker"].get(0, 99) < 1.0:
                        fresh = True
                        break
                time.sleep(0.2)
            assert counters().get("chaos_drop") >= 2, (
                "scheduler-link faults never injected"
            )
            assert fresh, "heartbeats did not recover after the drops"
            with w._sched_cb_lock:
                assert not w._sched_dead  # link never died: drops only
            w.close()
            srv.stop()
        finally:
            reset_fault_budget(None)
            _restore_env(old)
            sched.stop()

    def test_addrbook_drop_injectable_on_scheduler_side(self, monkeypatch):
        """The scheduler's accepted control connections are chaos-wrapped
        too (BYTEPS_CHAOS_SCHED), so scheduler→node frames (ADDRBOOK)
        are deterministically faultable: the first book is dropped, a
        re-register with the same uid gets the next one."""
        from byteps_tpu.comm.chaos import reset_fault_budget

        old = _set_env({
            "BYTEPS_VAN": "chaos:tcp",
            "BYTEPS_CHAOS_SCHED": "1",
            "BYTEPS_CHAOS_OPS": "ADDRBOOK",
            "BYTEPS_CHAOS_DROP": "1.0",
            "BYTEPS_CHAOS_SEED": "5",
            "BYTEPS_CHAOS_TARGET_PORT": "0",
        })
        counters().reset()
        reset_fault_budget(1)
        sched = Scheduler(1, 0, host="127.0.0.1")
        sched.start()
        try:
            payload = {"role": "worker", "host": "", "port": 0,
                       "uid": "book-drop-w0", "num_workers": 1,
                       "num_servers": 0}
            s1 = socket.create_connection(("127.0.0.1", sched.port), timeout=5)
            s1.settimeout(2)
            send_message(s1, Message(
                Op.REGISTER, payload=json.dumps(payload).encode()
            ))
            with pytest.raises(OSError):  # book dropped → recv times out
                recv_message(s1)
            assert counters().get("chaos_drop") == 1
            # budget spent: the rejoin's recovery book is delivered
            s2, resp = _register_raw(sched.port, payload, timeout=5)
            book = json.loads(resp.payload.decode())
            assert book["rank"] == 0 and book["is_recovery"] is True
            s1.close()
            s2.close()
        finally:
            reset_fault_budget(None)
            _restore_env(old)
            sched.stop()


class TestRejoinGraceWindow:
    def test_partial_population_adopted_after_window(self):
        """A reborn scheduler whose window expires with ranks missing
        adopts the re-registered subset (rank hints honored, epochs
        fenced) instead of stranding the survivors forever."""
        sched = Scheduler(num_workers=2, num_servers=0, host="127.0.0.1",
                          rejoin_window=0.6)
        sched.start()
        try:
            s1 = socket.create_connection(("127.0.0.1", sched.port), timeout=5)
            s1.settimeout(10)
            t0 = time.monotonic()
            send_message(s1, Message(Op.REGISTER, payload=json.dumps({
                "role": "worker", "host": "", "port": 0, "uid": "grace-w1",
                "num_workers": 2, "num_servers": 0,
                "last_rank": 1, "epoch": 2, "map_epoch": 3,
            }).encode()))
            book = json.loads(recv_message(s1).payload.decode())
            waited = time.monotonic() - t0
            assert waited >= 0.5, "book shipped before the grace window"
            assert book["rank"] == 1  # hint honored
            assert book["num_workers"] == 1  # partial population adopted
            assert book["map_epoch"] > 3 and book["epoch"] > 2
            assert sched.num_workers == 1
            assert sched.eviction_totals == {"worker": 0, "server": 0}

            # a late reconnector is re-admitted at its old rank and the
            # expectation grows back
            s0, resp = _register_raw(sched.port, {
                "role": "worker", "host": "", "port": 0, "uid": "grace-w0",
                "num_workers": 2, "num_servers": 0,
                "last_rank": 0, "epoch": 2, "map_epoch": 3,
            }, timeout=5)
            late = json.loads(resp.payload.decode())
            assert late["rank"] == 0 and late["is_recovery"] is True
            assert sched.num_workers == 2
            s0.close()
            s1.close()
        finally:
            sched.stop()

    def test_fresh_first_boot_never_arms_the_window(self):
        """Feature-off parity: first-boot registrants carry no rejoin
        report, so the grace timer must never start and bring-up waits
        for the full population exactly as before."""
        sched = Scheduler(num_workers=2, num_servers=0, host="127.0.0.1",
                          rejoin_window=0.3)
        sched.start()
        try:
            s1 = socket.create_connection(("127.0.0.1", sched.port), timeout=5)
            s1.settimeout(1.0)
            send_message(s1, Message(Op.REGISTER, payload=json.dumps({
                "role": "worker", "host": "", "port": 0, "uid": "boot-w0",
                "num_workers": 2, "num_servers": 0,
            }).encode()))
            with pytest.raises(OSError):  # no book: population incomplete
                recv_message(s1)
            assert sched._grace_thread is None
            assert not sched._addrbook_sent
            s1.close()
        finally:
            sched.stop()
