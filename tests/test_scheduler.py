"""Unit tests for the priority scheduler and ready table (the reference has
none for these — SURVEY §4 calls for real unit tests here)."""

import threading
import time

import pytest

from byteps_tpu.common.types import QueueType, TensorTableEntry
from byteps_tpu.core.ready_table import ReadyTable
from byteps_tpu.core.scheduler import ScheduledQueue


def make_task(key, priority=0, length=10):
    return TensorTableEntry(
        tensor_name=f"t{key}", key=key, priority=priority, length=length,
        queue_list=[QueueType.PUSH],
    )


class TestScheduledQueue:
    def test_priority_order(self):
        # (priority desc, key asc) — scheduled_queue.cc:82-102
        q = ScheduledQueue(QueueType.PUSH)
        q.add_task(make_task(3, priority=-3))
        q.add_task(make_task(1, priority=0))
        q.add_task(make_task(2, priority=-1))
        assert q.get_task().key == 1
        assert q.get_task().key == 2
        assert q.get_task().key == 3

    def test_key_tiebreak(self):
        q = ScheduledQueue(QueueType.PUSH)
        q.add_task(make_task(9, priority=0))
        q.add_task(make_task(4, priority=0))
        assert q.get_task().key == 4

    def test_credit_blocks_oversized(self):
        # BYTEPS_SCHEDULING_CREDIT (scheduled_queue.cc:26-46)
        q = ScheduledQueue(QueueType.PUSH, credit_bytes=100, itemsize=4)
        big = make_task(1, length=100)   # 400B > 100B credit
        q.add_task(big)
        assert q.get_task(timeout=0.05) is None
        small = make_task(2, length=10)  # 40B fits
        q.add_task(small)
        got = q.get_task(timeout=0.5)
        assert got is not None and got.key == 2

    def test_credit_returned_on_finish(self):
        q = ScheduledQueue(QueueType.PUSH, credit_bytes=100, itemsize=4)
        t1 = make_task(1, length=20)  # 80B
        t2 = make_task(2, length=20)  # 80B — doesn't fit while t1 in flight
        q.add_task(t1)
        q.add_task(t2)
        got1 = q.get_task(timeout=0.5)
        assert got1.key == 1
        assert q.get_task(timeout=0.05) is None  # out of credit
        q.report_finish(got1)  # credits returned (scheduled_queue.cc:197-203)
        got2 = q.get_task(timeout=0.5)
        assert got2 is not None and got2.key == 2

    def test_ready_table_gate(self):
        # tasks whose key isn't ready are skipped (scheduled_queue.cc:125-163)
        table = ReadyTable(ready_count=2)
        q = ScheduledQueue(QueueType.PUSH, ready_table=table)
        q.add_task(make_task(7))
        assert q.get_task(timeout=0.05) is None
        table.add_ready_count(7)
        assert q.get_task(timeout=0.05) is None
        table.add_ready_count(7)
        q.notify()
        got = q.get_task(timeout=0.5)
        assert got is not None and got.key == 7
        # dequeue clears the count for the next round
        assert not table.is_ready(7)

    def test_get_by_key(self):
        q = ScheduledQueue(QueueType.PUSH)
        q.add_task(make_task(1))
        q.add_task(make_task(2))
        assert q.get_task_by_key(2).key == 2
        assert q.get_task_by_key(99) is None


class TestReadyTable:
    def test_counts(self):
        t = ReadyTable(ready_count=3)
        assert not t.is_ready(5)
        t.add_ready_count(5)
        t.add_ready_count(5, 2)
        assert t.is_ready(5)
        t.clear_ready_count(5)
        assert not t.is_ready(5)
