"""Server-side optimizer plane (docs/architecture.md "Server-side
optimizer"): workers push gradients, the server runs the update rule,
workers pull UPDATED PARAMETERS — worker-side optimizer state drops to
zero bytes.

Layers under test:

- wire level: the INIT profile extension (bit 1 + rule block) declares a
  per-key update rule; the seed round adopts the workers' initial params
  VERBATIM (bitwise — never an average of identical copies); gradient
  rounds fire the rule exactly once per completed round
- the acceptance pin: worker-side vs server-side SGD / momentum
  trajectories are BITWISE identical across {unfused, fused} on the
  python engine — the worker-side reference here is an INDEPENDENT numpy
  implementation mirroring the engine's _finalize op order (divide, then
  the optimizer), not a re-import of the server's rule classes
- Adam: a fixed-seed trajectory pins to a frozen digest — any change to
  the update math, the bias-correction schedule, or the seed semantics
  breaks the literal
- exactly-once: a REPLAYED gradient push (journal retransmit, retry
  storm) dedupes before it can re-count toward the round barrier, so the
  rule never fires twice for one round and params do not move
- async profile (bit 0 | bit 1): the rule fires per push under the SSP
  gate; each worker's first push is its parameter seed (the per-worker
  seed ledger survives re-init barriers, so a rejoiner's pushes go
  straight back to gradient semantics)
- malformed / unsupported declarations: unknown rule names and the
  native C++ engine both answer a clean status=1 INIT echo (the
  Python-engine fallback rule) — never a silent downgrade to SUM
- engine level: a full cluster with ``byteps_server_opt`` declare
  kwargs pulls parameters (no worker-side divide), bitwise against the
  same independent reference; DistributedOptimizer(server_side=True)
  drives the same plane through optim.server_step
"""

import hashlib
import struct
import threading

import numpy as np
import pytest

from byteps_tpu.common.config import Config
from byteps_tpu.common.types import DataType, RequestType, get_command_type
from byteps_tpu.comm.rendezvous import Scheduler
from byteps_tpu.comm.transport import (
    Message,
    Op,
    close_socket,
    connect,
    decode_fused_reply,
    encode_fused_push,
    encode_server_opt_block,
    recv_message,
    send_message,
)
from byteps_tpu.core.telemetry import counters
from byteps_tpu.server.server import PSServer
from byteps_tpu.server.update_rules import canonical_hp, make_rule

CMD_F32 = get_command_type(RequestType.DEFAULT_PUSH_PULL, int(DataType.FLOAT32))
F32 = int(DataType.FLOAT32)

KEY_A = 7 << 16
KEY_B = 9 << 16
N = 64


# --- wire helpers ----------------------------------------------------------


def _opt_init_payload(n, rule, hp=None, async_profile=False, staleness=-1):
    profile = (1 if async_profile else 0) | 2
    payload = struct.pack("!QI", n, F32)
    payload += struct.pack("!Bi", profile, int(staleness))
    payload += encode_server_opt_block(rule, canonical_hp(hp or {}))
    return payload


def _init_opt_key(socks_flags, key, n, rule, hp=None,
                  async_profile=False, staleness=-1, token=77):
    payload = _opt_init_payload(n, rule, hp, async_profile, staleness)
    for i, (sock, flag) in enumerate(socks_flags):
        send_message(sock, Message(Op.INIT, key=key, seq=100 + i, flags=flag,
                                   version=token, payload=payload))
    for sock, _ in socks_flags:
        r = recv_message(sock)
        assert r.op == Op.INIT and r.status == 0


def _push(sock, key, flag, version, arr, seq):
    send_message(sock, Message(Op.PUSH, key=key, seq=seq, flags=flag,
                               cmd=CMD_F32, version=version,
                               payload=arr.tobytes()))


def _pull(sock, key, version, seq):
    send_message(sock, Message(Op.PULL, key=key, seq=seq, cmd=CMD_F32,
                               version=version))
    r = recv_message(sock)
    assert r.op == Op.PULL
    return np.frombuffer(r.payload, dtype=np.float32)


def _wire_server(num_workers=2):
    srv = PSServer(Config(num_worker=num_workers, num_server=1))
    srv.start(register=False)
    return srv


# --- the independent worker-side reference ---------------------------------
# Mirrors the WORKER-side op order exactly: the engine's _finalize divides
# the pulled sum (float32 array / python int), then the optimizer applies
# its in-place float32 update.  Deliberately NOT built on
# server.update_rules — this is the other half of the parity claim.


class _WorkerSideRef:
    def __init__(self, rule, hp, x0):
        self.rule = rule
        self.lr = np.float32(hp.get("lr", 0.001 if rule == "adam" else 0.01))
        self.params = x0.copy()
        if rule == "momentum":
            self.mu = np.float32(hp.get("momentum", 0.9))
            self.m = np.zeros_like(x0)
        if rule == "adam":
            self.b1 = np.float32(hp.get("b1", 0.9))
            self.b2 = np.float32(hp.get("b2", 0.999))
            self.eps = np.float32(hp.get("eps", 1e-8))
            self.m = np.zeros_like(x0)
            self.v = np.zeros_like(x0)
        self.t = 0

    def step(self, grad_sum, num_workers):
        grad = grad_sum / num_workers  # the engine _finalize divide
        self.t += 1
        if self.rule == "sgd":
            self.params -= self.lr * grad
        elif self.rule == "momentum":
            np.multiply(self.m, self.mu, out=self.m)
            self.m += grad
            self.params -= self.lr * self.m
        else:  # adam
            one = np.float32(1)
            np.multiply(self.m, self.b1, out=self.m)
            self.m += (one - self.b1) * grad
            np.multiply(self.v, self.b2, out=self.v)
            self.v += (one - self.b2) * (grad * grad)
            m_hat = self.m / (one - self.b1 ** np.float32(self.t))
            v_hat = self.v / (one - self.b2 ** np.float32(self.t))
            self.params -= self.lr * (m_hat / (np.sqrt(v_hat) + self.eps))
        return self.params


# --- wire-level bitwise trajectories ---------------------------------------


class TestWireBitwiseTrajectory:
    """Worker-side vs server-side trajectories, two real workers on raw
    sockets.  Two workers keep float addition commutative (a+b == b+a
    bitwise), so arrival order cannot smear the parity claim."""

    def _run_lane(self, rule, hp, fused, rounds=5, seed=42):
        srv = _wire_server(num_workers=2)
        rng = np.random.default_rng(seed)
        x0 = {k: rng.standard_normal(N).astype(np.float32)
              for k in (KEY_A, KEY_B)}
        refs = {k: _WorkerSideRef(rule, hp, x0[k]) for k in (KEY_A, KEY_B)}
        digest = hashlib.sha256()
        w1 = connect(srv.host, srv.port)
        w2 = connect(srv.host, srv.port)
        w1.settimeout(15)
        w2.settimeout(15)
        try:
            for k in (KEY_A, KEY_B):
                _init_opt_key([(w1, 1), (w2, 2)], k, N, rule, hp)
            # round 1: the parameter seed — every worker pushes the SAME
            # initial params; the server adopts them verbatim
            for k in (KEY_A, KEY_B):
                _push(w1, k, 1, 1, x0[k], seq=1)
                _push(w2, k, 2, 1, x0[k], seq=1)
                assert recv_message(w1).op == Op.PUSH
                assert recv_message(w2).op == Op.PUSH
                np.testing.assert_array_equal(_pull(w1, k, 1, seq=2), x0[k])
            # gradient rounds
            for r in range(2, 2 + rounds):
                grads = {
                    (k, wid): rng.standard_normal(N).astype(np.float32)
                    for k in (KEY_A, KEY_B) for wid in (1, 2)
                }
                if fused:
                    for sock, wid in ((w1, 1), (w2, 2)):
                        frame = encode_fused_push([
                            (k, CMD_F32, r, grads[(k, wid)].tobytes())
                            for k in (KEY_A, KEY_B)
                        ])
                        send_message(sock, Message(
                            Op.FUSED, key=KEY_A, seq=10 * r + wid,
                            flags=wid, cmd=2, payload=frame))
                    got = {}
                    for sock in (w1, w2):
                        msg = recv_message(sock)
                        assert msg.op == Op.FUSED
                        for k, _ver, payload in decode_fused_reply(
                                msg.payload):
                            got[k] = np.frombuffer(payload,
                                                   dtype=np.float32)
                else:
                    for k in (KEY_A, KEY_B):
                        _push(w1, k, 1, r, grads[(k, 1)], seq=10 * r)
                        _push(w2, k, 2, r, grads[(k, 2)], seq=10 * r)
                        assert recv_message(w1).op == Op.PUSH
                        assert recv_message(w2).op == Op.PUSH
                    got = {k: _pull(w1, k, r, seq=10 * r + 5)
                           for k in (KEY_A, KEY_B)}
                for k in (KEY_A, KEY_B):
                    gs = grads[(k, 1)].copy()
                    gs += grads[(k, 2)]  # COPY_FIRST then SUM_RECV order
                    want = refs[k].step(gs, 2)
                    np.testing.assert_array_equal(got[k], want)
                    digest.update(got[k].tobytes())
            assert srv._keys[KEY_A].opt_step == 1 + rounds
        finally:
            close_socket(w1)
            close_socket(w2)
            srv.stop()
        return digest.hexdigest()

    @pytest.mark.parametrize("rule,hp", [
        ("sgd", {"lr": 0.05}),
        ("momentum", {"lr": 0.05, "momentum": 0.9}),
    ])
    def test_worker_vs_server_bitwise_fused_and_unfused(self, rule, hp):
        d_unfused = self._run_lane(rule, hp, fused=False)
        d_fused = self._run_lane(rule, hp, fused=True)
        # fusion changes where bytes ride, never what they say
        assert d_unfused == d_fused

    def test_adam_matches_independent_reference(self):
        self._run_lane("adam", {"lr": 0.002}, fused=False)

    def test_adam_frozen_digest(self):
        """Fixed-seed Adam trajectory pinned to a literal — the update
        math, bias-correction schedule, and seed semantics are all
        load-bearing for checkpoint/trajectory compatibility."""
        d = self._run_lane("adam", {}, fused=False, rounds=6, seed=1234)
        assert d == ADAM_FROZEN_DIGEST, d


ADAM_FROZEN_DIGEST = (
    "ddfcbd90910d65d3fa4ba19531e2a0a137717a02c3144d2f68b93b16862fe1b2"
)


# --- exactly-once under replay ---------------------------------------------


class TestExactlyOnce:
    def test_replayed_push_never_double_applies(self):
        srv = _wire_server(num_workers=2)
        rng = np.random.default_rng(7)
        x0 = rng.standard_normal(N).astype(np.float32)
        ref = _WorkerSideRef("momentum", {"lr": 0.1}, x0)
        w1 = connect(srv.host, srv.port)
        w2 = connect(srv.host, srv.port)
        w1.settimeout(15)
        w2.settimeout(15)
        try:
            _init_opt_key([(w1, 1), (w2, 2)], KEY_A, N, "momentum",
                          {"lr": 0.1})
            _push(w1, KEY_A, 1, 1, x0, seq=1)
            _push(w2, KEY_A, 2, 1, x0, seq=1)
            assert recv_message(w1).op == Op.PUSH
            assert recv_message(w2).op == Op.PUSH
            g1 = rng.standard_normal(N).astype(np.float32)
            g2 = rng.standard_normal(N).astype(np.float32)
            _push(w1, KEY_A, 1, 2, g1, seq=2)
            _push(w2, KEY_A, 2, 2, g2, seq=2)
            assert recv_message(w1).op == Op.PUSH
            assert recv_message(w2).op == Op.PUSH
            gs = g1.copy()
            gs += g2
            want = ref.step(gs, 2).copy()
            np.testing.assert_array_equal(_pull(w1, KEY_A, 2, seq=3), want)
            before = counters().snapshot().get("push_dedup", 0)
            step_before = srv._keys[KEY_A].opt_step
            # the journal retransmit: the SAME round-2 push again — the
            # ledger dedupes BEFORE barrier counting, so the rule cannot
            # fire a second time for the round
            _push(w1, KEY_A, 1, 2, g1, seq=4)
            assert recv_message(w1).op == Op.PUSH
            assert counters().snapshot().get("push_dedup", 0) == before + 1
            assert srv._keys[KEY_A].opt_step == step_before
            np.testing.assert_array_equal(_pull(w1, KEY_A, 2, seq=5), want)
            # ...and the trajectory continues undamaged
            g3 = rng.standard_normal(N).astype(np.float32)
            _push(w1, KEY_A, 1, 3, g3, seq=6)
            _push(w2, KEY_A, 2, 3, g3, seq=6)
            assert recv_message(w1).op == Op.PUSH
            assert recv_message(w2).op == Op.PUSH
            gs3 = g3.copy()
            gs3 += g3
            np.testing.assert_array_equal(
                _pull(w1, KEY_A, 3, seq=7), ref.step(gs3, 2))
        finally:
            close_socket(w1)
            close_socket(w2)
            srv.stop()


# --- async profile ---------------------------------------------------------


class TestAsyncServerOpt:
    def test_per_push_updates_and_seed_ledger_survives_reinit(self):
        srv = _wire_server(num_workers=1)
        rng = np.random.default_rng(11)
        x0 = rng.standard_normal(N).astype(np.float32)
        ref = _WorkerSideRef("sgd", {"lr": 0.05}, x0)
        w = connect(srv.host, srv.port)
        w.settimeout(15)
        try:
            _init_opt_key([(w, 1)], KEY_A, N, "sgd", {"lr": 0.05},
                          async_profile=True, staleness=-1)
            # first push = the worker's parameter seed, adopted verbatim
            _push(w, KEY_A, 1, 1, x0, seq=1)
            assert recv_message(w).op == Op.PUSH
            np.testing.assert_array_equal(_pull(w, KEY_A, 1, seq=2), x0)
            for r in range(2, 5):
                g = rng.standard_normal(N).astype(np.float32)
                _push(w, KEY_A, 1, r, g, seq=10 * r)
                assert recv_message(w).op == Op.PUSH
                np.testing.assert_array_equal(
                    _pull(w, KEY_A, r, seq=10 * r + 1), ref.step(g, 1))
            # a rejoiner re-runs the init barrier with the SAME config:
            # slots, step count AND the per-worker seed ledger survive —
            # its next push is a gradient, not a fresh seed
            _init_opt_key([(w, 1)], KEY_A, N, "sgd", {"lr": 0.05},
                          async_profile=True, staleness=-1, token=78)
            g = rng.standard_normal(N).astype(np.float32)
            _push(w, KEY_A, 1, 5, g, seq=50)
            assert recv_message(w).op == Op.PUSH
            np.testing.assert_array_equal(
                _pull(w, KEY_A, 5, seq=51), ref.step(g, 1))
        finally:
            close_socket(w)
            srv.stop()


# --- declaration hygiene ----------------------------------------------------


class TestDeclaration:
    def test_unknown_rule_fails_at_declare_time(self):
        # the rule registry is local: a typo'd name errors at
        # bps.declare_tensor, before anything travels to a server
        import byteps_tpu as bps

        with pytest.raises(ValueError, match="adagrad"):
            bps.declare_tensor("sopt.typo", byteps_server_opt="adagrad")
        # the off-spellings and known rules still pass validation
        bps.declare_tensor("sopt.off_ok", byteps_server_opt="off")
        bps.declare_tensor("sopt.known_ok", byteps_server_opt="adam")

    def test_unknown_rule_is_clean_status_reject(self):
        srv = _wire_server(num_workers=1)
        w = connect(srv.host, srv.port)
        w.settimeout(15)
        try:
            before = counters().snapshot().get("server_opt_reject", 0)
            payload = _opt_init_payload(N, "adagrad")
            send_message(w, Message(Op.INIT, key=KEY_A, seq=1, flags=1,
                                    version=77, payload=payload))
            r = recv_message(w)
            assert r.op == Op.INIT and r.status != 0
            assert counters().snapshot().get(
                "server_opt_reject", 0) == before + 1
            # the stream stayed framed: a plain PING still round-trips
            send_message(w, Message(Op.PING, seq=2))
            assert recv_message(w).op == Op.PING
        finally:
            close_socket(w)
            srv.stop()

    def test_reinit_without_profile_returns_key_to_sum(self):
        srv = _wire_server(num_workers=1)
        w = connect(srv.host, srv.port)
        w.settimeout(15)
        try:
            _init_opt_key([(w, 1)], KEY_A, N, "sgd", {"lr": 0.5})
            assert srv._keys[KEY_A].opt_rule is not None
            # plain 12-byte re-init: the key returns to SUM semantics
            payload = struct.pack("!QI", N, F32)
            send_message(w, Message(Op.INIT, key=KEY_A, seq=9, flags=1,
                                    version=78, payload=payload))
            assert recv_message(w).op == Op.INIT
            ks = srv._keys[KEY_A]
            assert ks.opt_rule is None and ks.opt_step == 0
            g = np.full(N, 2.0, dtype=np.float32)
            _push(w, KEY_A, 1, 1, g, seq=10)
            assert recv_message(w).op == Op.PUSH
            np.testing.assert_array_equal(_pull(w, KEY_A, 1, seq=11), g)
        finally:
            close_socket(w)
            srv.stop()

    def test_native_engine_rejects_with_counter(self):
        from conftest import have_native_parity_server

        if not have_native_parity_server():
            pytest.skip("native lib not built")
        from byteps_tpu.native import get_lib, native_server_counters

        lib = get_lib()
        port = lib.bps_native_server_start(0, 1, 0)
        assert port > 0
        try:
            s = connect("127.0.0.1", port)
            send_message(s, Message(Op.INIT, key=KEY_A, seq=1, flags=1,
                                    version=7,
                                    payload=_opt_init_payload(8, "sgd")))
            r = recv_message(s)
            assert r.op == Op.INIT and r.status != 0
            # the stream stayed framed
            send_message(s, Message(Op.PING, seq=2))
            assert recv_message(s).op == Op.PING
            ctrs = native_server_counters(port)
            assert ctrs.get("native_server_opt_reject", 0) >= 1
            close_socket(s)
        finally:
            lib.bps_native_server_stop(port)


# --- engine level -----------------------------------------------------------


def _reset_runtime():
    from byteps_tpu.common import config as _config
    from byteps_tpu.common import registry as _registry
    from byteps_tpu.core import state as _state

    _state.shutdown_state()
    _registry.reset_registry()
    _config.clear_config()


def _cluster(monkeypatch, threshold=0):
    monkeypatch.setenv("BYTEPS_FUSION_THRESHOLD", str(threshold))
    monkeypatch.setenv("BYTEPS_FUSION_CYCLE_MS", "2")
    sched = Scheduler(num_workers=1, num_servers=1, host="127.0.0.1")
    sched.start()
    monkeypatch.setenv("DMLC_PS_ROOT_URI", "127.0.0.1")
    monkeypatch.setenv("DMLC_PS_ROOT_PORT", str(sched.port))
    monkeypatch.setenv("DMLC_NUM_WORKER", "1")
    monkeypatch.setenv("DMLC_NUM_SERVER", "1")
    monkeypatch.setenv("BYTEPS_FORCE_DISTRIBUTED", "1")
    monkeypatch.delenv("BYTEPS_SERVER_NATIVE", raising=False)
    srv = PSServer(Config.from_env())
    threading.Thread(target=srv.start, daemon=True).start()
    return sched, srv


class TestEngineLane:
    def test_declare_kwargs_pull_params_bitwise(self, monkeypatch):
        """Full cluster: byteps_server_opt declare kwargs — the engine
        ships the profile at INIT, forces average=False (the pull IS the
        parameters), and the pulled trajectory is bitwise the
        independent worker-side reference."""
        sched, srv = _cluster(monkeypatch)
        import byteps_tpu as bps

        try:
            bps.init()
            rng = np.random.default_rng(5)
            x0 = rng.standard_normal(256).astype(np.float32)
            ref = _WorkerSideRef("momentum", {"lr": 0.01}, x0)
            bps.declare_tensor(
                "sopt.w", byteps_server_opt="momentum",
                byteps_server_opt_hp={"lr": 0.01},
            )
            # round 1: the seed — push params, pull them back verbatim
            got = np.asarray(bps.synchronize(
                bps.push_pull_async(x0, name="sopt.w")))
            np.testing.assert_array_equal(got, x0)
            for _ in range(4):
                g = rng.standard_normal(256).astype(np.float32)
                got = np.asarray(bps.synchronize(
                    bps.push_pull_async(g, name="sopt.w")))
                np.testing.assert_array_equal(got, ref.step(g, 1))
            snap = counters().snapshot()
            assert snap.get("server_opt_updates", 0) >= 4
        finally:
            bps.shutdown()
            _reset_runtime()
            srv.stop()
            sched.stop()

    def test_env_knob_applies_to_all_tensors(self, monkeypatch):
        """BYTEPS_SERVER_OPT / _HP declare the profile job-wide; a
        per-tensor byteps_server_opt="off" opts a tensor back out."""
        monkeypatch.setenv("BYTEPS_SERVER_OPT", "sgd")
        monkeypatch.setenv("BYTEPS_SERVER_OPT_HP", '{"lr": 0.25}')
        sched, srv = _cluster(monkeypatch)
        import byteps_tpu as bps

        try:
            bps.init()
            x0 = np.ones(32, dtype=np.float32)
            ref = _WorkerSideRef("sgd", {"lr": 0.25}, x0)
            got = np.asarray(bps.synchronize(
                bps.push_pull_async(x0, name="sopt.env")))
            np.testing.assert_array_equal(got, x0)
            g = np.full(32, 2.0, dtype=np.float32)
            got = np.asarray(bps.synchronize(
                bps.push_pull_async(g, name="sopt.env")))
            np.testing.assert_array_equal(got, ref.step(g, 1))
            # opted-out tensor keeps plain SUM semantics (1 worker:
            # average divides by 1 — the sum comes back unchanged)
            bps.declare_tensor("sopt.plain", byteps_server_opt="off")
            got = np.asarray(bps.synchronize(
                bps.push_pull_async(g, name="sopt.plain")))
            np.testing.assert_array_equal(got, g)
        finally:
            bps.shutdown()
            _reset_runtime()
            srv.stop()
            sched.stop()

    def test_distributed_optimizer_server_side(self, monkeypatch):
        """DistributedOptimizer(server_side=True): server_step seeds the
        params on first call, then maps grads → updated params through
        the server's rule — no optax chain, no worker-side slots."""
        jax = pytest.importorskip("jax")
        sched, srv = _cluster(monkeypatch)
        import byteps_tpu as bps
        from byteps_tpu.optim import DistributedOptimizer

        try:
            bps.init()
            rng = np.random.default_rng(3)
            params = {
                "w": jax.numpy.asarray(
                    rng.standard_normal(64).astype(np.float32)),
                "b": jax.numpy.asarray(
                    rng.standard_normal(8).astype(np.float32)),
            }
            refs = {
                k: _WorkerSideRef("sgd", {"lr": 0.1}, np.asarray(v))
                for k, v in params.items()
            }
            opt = DistributedOptimizer(
                server_side=True, server_rule="sgd",
                server_hp={"lr": 0.1})
            assert opt._tx is None  # no worker-side optax chain at all
            for _ in range(3):
                grads = {
                    k: jax.numpy.asarray(
                        rng.standard_normal(v.shape[0]).astype(np.float32))
                    for k, v in params.items()
                }
                params = opt.server_step(params, grads)
                for k in refs:
                    np.testing.assert_array_equal(
                        np.asarray(params[k]),
                        refs[k].step(np.asarray(grads[k]), 1))
        finally:
            bps.shutdown()
            _reset_runtime()
            srv.stop()
            sched.stop()

    def test_rowsparse_rejected(self, monkeypatch):
        sched, srv = _cluster(monkeypatch)
        import byteps_tpu as bps

        try:
            bps.init()
            from byteps_tpu import api as _api

            _api.declare_tensor("sopt.rs", byteps_server_opt="sgd")
            with pytest.raises(ValueError, match="row-sparse"):
                _api.push_pull_rowsparse_async(
                    np.array([0, 1], dtype=np.int64),
                    np.zeros((2, 8), dtype=np.float32),
                    name="sopt.rs", total_rows=4)
        finally:
            bps.shutdown()
            _reset_runtime()
            srv.stop()
            sched.stop()
