"""Link shaping (comm/shaping.py): the DCN-emulation knob.

Lower-bound timing asserts only — on the shared 1-core CI box an upper
bound on wall time flakes, but "shaping added at least its configured
cost" cannot be broken by contention.  Two deliberate exceptions carry
multi-hundred-ms slack and are marked inline.
"""

import os
import socket
import threading
import time

import numpy as np
import pytest

from byteps_tpu.comm.shaping import ShapedSocket, maybe_shape, shaping_params


def _pair():
    a, b = socket.socketpair()
    return a, b


class TestShapedSocket:
    def test_rate_limits_throughput(self):
        a, b = _pair()
        # 2 MB at 100 MB/s ⇒ ≥ 20ms of serialization
        shaped = ShapedSocket(a, delay_s=0.0, rate_bps=100e6, buf_bytes=1 << 22)
        payload = b"x" * (2 << 20)
        got = bytearray()

        def rx():
            while len(got) < len(payload):
                chunk = b.recv(1 << 20)
                if not chunk:
                    return
                got.extend(chunk)

        t = threading.Thread(target=rx, daemon=True)
        t.start()
        t0 = time.monotonic()
        shaped.sendall(payload)
        t.join(timeout=10)
        elapsed = time.monotonic() - t0
        assert bytes(got) == payload
        assert elapsed >= 0.016  # 80% of the 20ms serialization time
        shaped.close()
        b.close()

    def test_delay_is_pipelined_not_blocking(self):
        """Propagation delay postpones delivery but does NOT occupy the
        sender: sendall returns immediately (the message rides the
        virtual wire) and the receiver sees it one delay later."""
        a, b = _pair()
        shaped = ShapedSocket(a, delay_s=1.0, rate_bps=0.0, buf_bytes=1 << 20)
        t0 = time.monotonic()
        shaped.sendall(b"ping")
        send_cost = time.monotonic() - t0
        # the one deliberate upper bound in this file: enqueue-only sendall
        # vs a 1s delay, with 0.5s of contention slack — if this flakes the
        # sender really did sleep the propagation delay
        assert send_cost < 0.5
        b.settimeout(10)
        data = b.recv(16)
        arrival = time.monotonic() - t0
        assert data == b"ping"
        assert arrival >= 0.8  # 80% of the 1s propagation delay
        shaped.close()
        b.close()

    def test_fifo_order_preserved(self):
        a, b = _pair()
        shaped = ShapedSocket(a, delay_s=0.005, rate_bps=500e6, buf_bytes=1 << 22)
        msgs = [bytes([i]) * (1 + (i * 37) % 1000) for i in range(32)]
        for m in msgs:
            shaped.sendall(m)
        want = b"".join(msgs)
        got = bytearray()
        b.settimeout(10)
        while len(got) < len(want):
            got.extend(b.recv(1 << 16))
        assert bytes(got) == want
        shaped.close()
        b.close()

    def test_backpressure_blocks_at_buffer_limit(self):
        """Once buf_bytes are in flight the sender blocks — the kernel
        socket-buffer analogue the scheduler benchmark relies on."""
        a, b = _pair()
        shaped = ShapedSocket(a, delay_s=0.0, rate_bps=10e6, buf_bytes=64 << 10)
        drained = bytearray()

        def rx():
            b.settimeout(10)
            while len(drained) < (1 << 20):
                try:
                    drained.extend(b.recv(1 << 16))
                except OSError:
                    return

        t = threading.Thread(target=rx, daemon=True)
        t.start()
        t0 = time.monotonic()
        for _ in range(16):  # 1 MB total at 10 MB/s ⇒ ≥ ~100ms serialized
            shaped.sendall(b"z" * (64 << 10))
        elapsed = time.monotonic() - t0
        # all but the last buffer's worth must have waited for the wire
        assert elapsed >= 0.07
        t.join(timeout=10)
        assert len(drained) == 1 << 20
        shaped.close()
        b.close()

    def test_throughput_governed_by_rate_not_buffer_over_delay(self):
        """Propagation delay must not occupy shaping-buffer space: with
        rate 50 MB/s, delay 100ms, buf 256KB, pushing 2MB is
        serialization-bound (~40ms + 100ms).  If buffered bytes were
        held until *delivery*, throughput would cap at buf/delay =
        2.56 MB/s and this send would take >0.8s."""
        a, b = _pair()
        shaped = ShapedSocket(a, delay_s=0.1, rate_bps=50e6, buf_bytes=256 << 10)
        total = 2 << 20
        got = bytearray()

        def rx():
            b.settimeout(10)
            while len(got) < total:
                got.extend(b.recv(1 << 16))

        t = threading.Thread(target=rx, daemon=True)
        t.start()
        t0 = time.monotonic()
        for _ in range(32):
            shaped.sendall(b"q" * (64 << 10))
        sender_done = time.monotonic() - t0
        t.join(timeout=10)
        assert len(got) == total
        # second deliberate upper bound (≥4x slack vs the ~0.9s bug mode)
        assert sender_done < 0.6, f"throughput capped by buf/delay: {sender_done:.3f}s"
        shaped.close()
        b.close()

    def test_send_error_surfaces_to_caller(self):
        a, b = _pair()
        shaped = ShapedSocket(a, delay_s=0.01, rate_bps=0.0, buf_bytes=1 << 20)
        b.close()
        shaped.sendall(b"doomed " * 100000)  # delivery fails in the thread
        with pytest.raises(ConnectionError):
            for _ in range(200):
                shaped.sendall(b"next")
                time.sleep(0.005)
        shaped.close()

    def test_maybe_shape_disabled_is_identity(self, monkeypatch):
        monkeypatch.delenv("BYTEPS_VAN_DELAY_MS", raising=False)
        monkeypatch.delenv("BYTEPS_VAN_RATE_MBPS", raising=False)
        a, b = _pair()
        assert maybe_shape(a) is a
        a.close()
        b.close()

    def test_params_parse(self, monkeypatch):
        monkeypatch.setenv("BYTEPS_VAN_DELAY_MS", "2.5")
        monkeypatch.setenv("BYTEPS_VAN_RATE_MBPS", "100")
        delay_s, rate_bps, buf = shaping_params()
        assert delay_s == pytest.approx(0.0025)
        assert rate_bps == pytest.approx(100e6)
        assert buf == 256 * 1024

    def test_canonical_rate_name_and_legacy_alias(self, monkeypatch):
        """BYTEPS_VAN_RATE_MBYTES_S is the canonical spelling (the unit
        was always megaBYTES/s — the old "MBPS" suffix was the naming
        trap); the legacy name still works, same unit, and the
        canonical name wins when both are set."""
        monkeypatch.delenv("BYTEPS_VAN_RATE_MBPS", raising=False)
        monkeypatch.setenv("BYTEPS_VAN_RATE_MBYTES_S", "25")
        assert shaping_params()[1] == pytest.approx(25e6)
        # legacy alias alone: same MB/s meaning
        monkeypatch.delenv("BYTEPS_VAN_RATE_MBYTES_S", raising=False)
        monkeypatch.setenv("BYTEPS_VAN_RATE_MBPS", "10")
        assert shaping_params()[1] == pytest.approx(10e6)
        # both set: canonical wins
        monkeypatch.setenv("BYTEPS_VAN_RATE_MBYTES_S", "40")
        assert shaping_params()[1] == pytest.approx(40e6)


class TestShapedCluster:
    def test_push_pull_correct_and_delayed_through_shaped_van(self, monkeypatch):
        """Full PS path over a shaped tcp van: results stay exact and a
        round-trip costs at least the configured 2×delay."""
        monkeypatch.setenv("BYTEPS_VAN_DELAY_MS", "40")
        monkeypatch.setenv("BYTEPS_VAN_RATE_MBPS", "500")
        # shaping must override the native client (which would silently
        # bypass the shaped Python lanes) — the rtt floor below proves it
        monkeypatch.setenv("BYTEPS_NATIVE_CLIENT", "1")
        from byteps_tpu.common.config import Config
        from byteps_tpu.comm.rendezvous import Scheduler
        from byteps_tpu.server.server import PSServer

        sched = Scheduler(num_workers=1, num_servers=1, host="127.0.0.1")
        sched.start()
        monkeypatch.setenv("DMLC_PS_ROOT_URI", "127.0.0.1")
        monkeypatch.setenv("DMLC_PS_ROOT_PORT", str(sched.port))
        monkeypatch.setenv("DMLC_NUM_WORKER", "1")
        monkeypatch.setenv("DMLC_NUM_SERVER", "1")
        monkeypatch.setenv("BYTEPS_FORCE_DISTRIBUTED", "1")
        srv = PSServer(Config.from_env())
        threading.Thread(target=srv.start, daemon=True).start()
        try:
            import byteps_tpu as bps

            bps.init()
            x = np.arange(256, dtype=np.float32)
            out = bps.push_pull(x, name="shaped.t")  # includes init round
            np.testing.assert_allclose(np.asarray(out), x)
            t0 = time.monotonic()
            out = bps.push_pull(x + 1, name="shaped.t")
            rtt = time.monotonic() - t0
            np.testing.assert_allclose(np.asarray(out), x + 1)
            # push (40ms) + pull response (40ms), 80% margin
            assert rtt >= 0.064, f"shaped round-trip too fast: {rtt:.4f}s"
            bps.shutdown()
        finally:
            srv.stop()
            sched.stop()
