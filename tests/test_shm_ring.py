"""Unit tests for the shared-memory ring and the shm van plumbing.

The PS-matrix coverage (tests/test_ps.py, "python-shm" param) proves the
van end to end; these pin the ring's byte-pipe semantics — wrap-around,
blocking, close/liveness — which the socket tests can't reach directly.
"""

import os
import socket
import threading
import time

import numpy as np
import pytest

from byteps_tpu.comm.shm_ring import ShmRing, create_ring_file


@pytest.fixture
def ring_pair():
    path = create_ring_file(1024, tag="test_")
    prod = ShmRing(path, "producer")
    cons = ShmRing(path, "consumer", unlink=True)
    yield prod, cons
    prod.close()
    cons.close()
    assert not os.path.exists(path)


def _read_exact(ring: ShmRing, n: int) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = ring.recv_into(view[got:], n - got)
        assert r > 0
        got += r
    return bytes(buf)


class TestShmRing:
    def test_roundtrip(self, ring_pair):
        prod, cons = ring_pair
        prod.write(b"hello world")
        assert _read_exact(cons, 11) == b"hello world"

    def test_wraparound_many_times(self, ring_pair):
        """Payloads larger than capacity must stream through (byte-pipe
        semantics); run enough data to wrap the 1KB ring repeatedly."""
        prod, cons = ring_pair
        rng = np.random.default_rng(0)
        data = rng.integers(0, 256, size=10_000, dtype=np.uint8).tobytes()
        out = {}

        def consume():
            out["data"] = _read_exact(cons, len(data))

        t = threading.Thread(target=consume)
        t.start()
        prod.write(data)
        t.join(10)
        assert out["data"] == data

    def test_interleaved_messages(self, ring_pair):
        prod, cons = ring_pair
        chunks = [bytes([i]) * (37 * (i + 1)) for i in range(20)]

        def produce():
            for c in chunks:
                prod.write(c)

        t = threading.Thread(target=produce)
        t.start()
        for c in chunks:
            assert _read_exact(cons, len(c)) == c
        t.join(10)

    def test_close_unblocks_reader(self, ring_pair):
        prod, cons = ring_pair
        result = {}

        def read():
            result["n"] = cons.recv_into(bytearray(8))

        t = threading.Thread(target=read)
        t.start()
        time.sleep(0.05)
        prod.mark_closed()
        t.join(5)
        assert result["n"] == 0

    def test_write_to_closed_peer_raises(self, ring_pair):
        prod, cons = ring_pair
        cons.mark_closed()
        # ring full + closed → ConnectionError, not a hang
        with pytest.raises(ConnectionError):
            prod.write(b"x" * 5000)

    def test_torn_write_detected_not_garbage(self, ring_pair):
        """A producer dying mid-frame (torn write: header promises more
        payload than ever arrives) must surface as a ConnectionError at
        the framing layer — never as garbage bytes handed to the caller
        and never as a hang (docs/robustness.md failure model)."""
        from byteps_tpu.comm.transport import HEADER_SIZE, Message, Op

        prod, cons = ring_pair
        frame = Message(Op.PUSH, key=9, seq=1, payload=b"z" * 300).encode()
        # half the payload lands, then the producer "crashes"
        prod.write(frame[: HEADER_SIZE + 150])
        prod.mark_closed()

        class _RingSock:
            """transport-facing shim: recv_into straight off the ring."""

            def recv_into(self, buf, nbytes=0):
                return cons.recv_into(buf, nbytes)

        from byteps_tpu.comm.transport import recv_message

        with pytest.raises(ConnectionError, match="peer closed"):
            recv_message(_RingSock())

    def test_torn_write_desync_rejected_by_magic(self, ring_pair):
        """If bytes DO follow a torn frame (a buggy producer resuming at
        the wrong offset), the next header parse must reject them via the
        magic check instead of trusting a garbage length field."""
        from byteps_tpu.comm.transport import HEADER_SIZE, Message, Op

        prod, cons = ring_pair
        good = Message(Op.PUSH, key=1, seq=1, payload=b"a" * 64).encode()
        prod.write(good[: HEADER_SIZE + 32])       # torn: 32 of 64 payload
        prod.write(b"\x00" * (HEADER_SIZE + 32))   # desynced continuation

        class _RingSock:
            def recv_into(self, buf, nbytes=0):
                return cons.recv_into(buf, nbytes)

        from byteps_tpu.comm.transport import recv_header, recv_message

        sock = _RingSock()
        recv_message(sock)  # the first frame parses (payload is garbage-free
        # here: 32 real + 32 zero bytes fill its declared length)
        with pytest.raises(ConnectionError, match="bad magic"):
            recv_header(sock)  # the NEXT header is desynced zeros → rejected
        prod.mark_closed()

    def test_wait_callback_breaks_stall(self, ring_pair):
        prod, cons = ring_pair
        # nothing ever arrives and the flag is never set: the wait hook
        # (the van's SIGKILL backstop) reporting peer-dead must end it
        assert cons.recv_into(bytearray(4), wait=lambda t: False) == 0
        with pytest.raises(ConnectionError):
            prod.write(b"x" * 2000, wait=lambda t: False)


class TestShmVanConnection:
    def test_message_roundtrip_and_kill_detection(self):
        from byteps_tpu.comm.transport import Message, Op, recv_message, send_message
        from byteps_tpu.comm.van import get_van

        van = get_van("shm")
        listener, host, port = van.listen("127.0.0.1")
        assert host.startswith("shm+unix://")
        accepted = {}

        def serve():
            conn, _ = listener.accept()
            accepted["conn"] = conn
            msg = recv_message(conn)
            send_message(conn, Message(Op.PULL, key=msg.key, payload=msg.payload * 2, seq=msg.seq))

        t = threading.Thread(target=serve, daemon=True)
        t.start()
        client = van.connect(host, port)
        payload = np.arange(100_000, dtype=np.float32).tobytes()  # > ring? no: 400KB < 16MB, but > one sendall chunk
        send_message(client, Message(Op.PUSH, key=7, payload=payload, seq=3))
        resp = recv_message(client)
        assert resp.key == 7 and resp.seq == 3
        assert resp.payload == payload * 2
        t.join(10)

        # server side drops the connection: the client's next read must
        # terminate, not spin (close_socket marks the rings closed)
        from byteps_tpu.comm.transport import close_socket

        close_socket(accepted["conn"])
        with pytest.raises(ConnectionError):
            recv_message(client)
        close_socket(client)
        listener.close()

    def test_failed_handshake_does_not_kill_accepts(self):
        """Clients that die or send garbage mid-handshake must neither
        kill the accept loop nor block other workers: accept() returns a
        lazy connection whose handshake failure surfaces per-connection
        as ConnectionError (the server loops drop such connections)."""
        from byteps_tpu.comm.transport import Message, Op, close_socket, recv_message, send_message
        from byteps_tpu.comm.van import get_van

        van = get_van("shm")
        listener, host, _ = van.listen("127.0.0.1")
        path = host[len("shm+unix://"):]
        results = []

        def serve_one():
            conn, _ = listener.accept()
            try:
                msg = recv_message(conn)
                send_message(conn, Message(Op.PING, seq=msg.seq))
                results.append("ok")
            except ConnectionError:
                results.append("dropped")
                close_socket(conn)

        threads = [threading.Thread(target=serve_one, daemon=True) for _ in range(3)]
        for t in threads:
            t.start()

        # saboteur 1: connects and dies before sending ring names
        s1 = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s1.connect(path)
        s1.close()
        # saboteur 2: announces a ring file that doesn't exist
        s2 = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s2.connect(path)
        bogus = b"/dev/shm/byteps_ring_nonexistent"
        import struct as _struct

        s2.sendall(_struct.pack("!H", len(bogus)) + bogus)
        s2.sendall(_struct.pack("!H", len(bogus)) + bogus)
        s2.close()

        # a healthy client must still get served
        client = van.connect(host, 0)
        send_message(client, Message(Op.PING, seq=9))
        assert recv_message(client).seq == 9
        for t in threads:
            t.join(15)
        assert sorted(results) == ["dropped", "dropped", "ok"]
        close_socket(client)
        listener.close()

    def test_ring_files_are_cleaned_up(self):
        from byteps_tpu.comm.transport import Message, Op, close_socket, recv_message, send_message
        from byteps_tpu.comm.van import get_van
        from byteps_tpu.comm.shm_ring import _shm_dir

        before = set(os.listdir(_shm_dir()))
        van = get_van("shm")
        listener, host, _ = van.listen("127.0.0.1")
        got = {}

        def serve():
            conn, _ = listener.accept()
            got["c"] = conn
            got["msg"] = recv_message(conn)  # completes the lazy handshake

        t = threading.Thread(target=serve, daemon=True)
        t.start()
        client = van.connect(host, 0)
        send_message(client, Message(Op.PING, seq=1))
        t.join(10)
        assert got["msg"].seq == 1
        # once the server has attached (first recv), both backing files
        # are unlinked — nothing may remain on disk while the
        # connection is live
        assert not {f for f in os.listdir(_shm_dir()) if f.startswith("byteps_ring_")} - before
        close_socket(client)
        close_socket(got["c"])
        listener.close()
