"""Short CI smoke of the randomized composition soak (tools/soak.py).

The full runs (150s × {tcp+shaped, shm, uds}: 25k+ rounds, 1000+
elastic resizes, device codecs + rowsparse + async mixed throughout)
are recorded in STATUS.md; CI keeps a seeded 8-second slice alive so
the harness itself cannot rot.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_soak_smoke():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "soak.py"),
         "--seconds", "8", "--seed", "11"],
        capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
    assert "SOAK OK" in out.stdout
