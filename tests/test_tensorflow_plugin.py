"""TensorFlow plugin tests (byteps/tensorflow parity surface).

Single-worker semantics: push_pull = identity, so DistributedOptimizer /
DistributedGradientTape must train exactly like their bare equivalents —
the reference's test pattern (tests/test_tensorflow_keras.py) with the
torch-plugin test structure."""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

import byteps_tpu.tensorflow as bps


def _data(seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((64, 8)).astype(np.float32)
    y = rng.standard_normal((64, 1)).astype(np.float32)
    return tf.constant(x), tf.constant(y)


def _model(seed=0):
    init = tf.keras.initializers.GlorotUniform(seed=seed)
    return tf.keras.Sequential(
        [
            tf.keras.layers.Dense(16, activation="relu", kernel_initializer=init),
            tf.keras.layers.Dense(1, kernel_initializer=init),
        ]
    )


class TestTFPushPull:
    def test_identity_eager(self):
        bps.init()
        t = tf.range(10, dtype=tf.float32)
        out = bps.push_pull(t, name="tf.t")
        np.testing.assert_allclose(np.asarray(out), np.arange(10, dtype=np.float32))
        bps.shutdown()

    def test_inside_tf_function(self):
        bps.init()

        @tf.function
        def fn(x):
            return bps.push_pull(x, name="tf.fn")

        out = fn(tf.ones(4))
        np.testing.assert_allclose(np.asarray(out), 1.0)
        bps.shutdown()

    def test_gradient_flows_through(self):
        """The registered gradient of push_pull is push_pull of the grad
        (ops.py:136-146): d/dx sum(push_pull(x)) == ones (1 worker)."""
        bps.init()
        x = tf.Variable(tf.ones(5))
        with tf.GradientTape() as tape:
            y = tf.reduce_sum(bps.push_pull(x, name="tf.grad", average=False))
        g = tape.gradient(y, x)
        np.testing.assert_allclose(np.asarray(g), 1.0)
        bps.shutdown()

    def test_fp16_compression_roundtrip(self):
        bps.init()
        t = tf.constant([1.0, 2.5, -3.25], dtype=tf.float32)
        out = bps.push_pull(t, name="tf.fp16", compression=bps.Compression.fp16)
        assert out.dtype == tf.float32
        np.testing.assert_allclose(np.asarray(out), [1.0, 2.5, -3.25])
        bps.shutdown()

    def test_broadcast_identity_single(self):
        bps.init()
        t = tf.constant([3.0, 4.0])
        out = bps.broadcast(t, root_rank=0, name="tf.b")
        np.testing.assert_allclose(np.asarray(out), [3.0, 4.0])
        bps.shutdown()


class TestTFDistributedGradientTape:
    def test_matches_bare_tape(self):
        bps.init()
        x, y = _data()
        m = _model(seed=1)
        m.build((None, 8))
        with tf.GradientTape() as bare:
            loss1 = tf.reduce_mean((m(x) - y) ** 2)
        g1 = bare.gradient(loss1, m.trainable_variables)

        dtape = bps.DistributedGradientTape(tf.GradientTape())
        with dtape:
            loss2 = tf.reduce_mean((m(x) - y) ** 2)
        g2 = dtape.gradient(loss2, m.trainable_variables)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
        bps.shutdown()


class TestTFDistributedOptimizer:
    def test_matches_bare_optimizer(self):
        bps.init()
        x, y = _data(2)
        m1, m2 = _model(seed=2), _model(seed=2)
        m1.build((None, 8))
        m2.build((None, 8))
        for v1, v2 in zip(m2.weights, m1.weights):
            v1.assign(v2)

        opt_ref = tf.keras.optimizers.SGD(0.05)
        opt_dist = bps.DistributedOptimizer(tf.keras.optimizers.SGD(0.05))
        # wrapper keeps the wrapped class's name (load_model contract)
        assert type(opt_dist).__name__ == "SGD"

        for _ in range(5):
            with tf.GradientTape() as t1:
                l1 = tf.reduce_mean((m1(x) - y) ** 2)
            opt_ref.apply_gradients(
                zip(t1.gradient(l1, m1.trainable_variables), m1.trainable_variables)
            )
            with tf.GradientTape() as t2:
                l2 = tf.reduce_mean((m2(x) - y) ** 2)
            opt_dist.apply_gradients(
                zip(t2.gradient(l2, m2.trainable_variables), m2.trainable_variables)
            )
        for p1, p2 in zip(m1.weights, m2.weights):
            np.testing.assert_allclose(
                np.asarray(p1), np.asarray(p2), rtol=1e-5, atol=1e-7
            )
        bps.shutdown()

    def test_model_fit_trains(self):
        """End-to-end keras compile/fit with the wrapped optimizer."""
        bps.init()
        x, y = _data(3)
        m = _model(seed=3)
        m.compile(optimizer=bps.DistributedOptimizer(tf.keras.optimizers.Adam(0.01)),
                  loss="mse")
        h = m.fit(np.asarray(x), np.asarray(y), epochs=3, batch_size=16, verbose=0)
        losses = h.history["loss"]
        assert losses[-1] < losses[0]
        bps.shutdown()

    def test_rejects_non_keras_optimizer(self):
        bps.init()
        with pytest.raises(ValueError, match="keras optimizer"):
            bps.DistributedOptimizer(object())
        bps.shutdown()


class TestTFAsyncMode:
    def test_async_parameter_store_training(self, monkeypatch):
        """BYTEPS_ENABLE_ASYNC: apply_gradients applies locally, then
        pushes weight DELTAS to the parameter store and adopts the pulled
        values (tensorflow/__init__.py:244-268 semantics). Single worker:
        store = sum of deltas = current weights, so training must proceed
        exactly like the bare optimizer."""
        import threading

        from byteps_tpu.common.config import Config
        from byteps_tpu.comm.rendezvous import Scheduler
        from byteps_tpu.server.server import PSServer

        monkeypatch.setenv("BYTEPS_ENABLE_ASYNC", "1")
        sched = Scheduler(num_workers=1, num_servers=1, host="127.0.0.1")
        sched.start()
        monkeypatch.setenv("DMLC_PS_ROOT_URI", "127.0.0.1")
        monkeypatch.setenv("DMLC_PS_ROOT_PORT", str(sched.port))
        monkeypatch.setenv("DMLC_NUM_WORKER", "1")
        monkeypatch.setenv("DMLC_NUM_SERVER", "1")
        monkeypatch.setenv("BYTEPS_FORCE_DISTRIBUTED", "1")
        srv = PSServer(Config.from_env())
        threading.Thread(target=srv.start, daemon=True).start()
        try:
            bps.init()
            # the async sync is gated on size() > 1 (single workers have
            # nothing to exchange); force the gate open so the delta-push/
            # pull path actually runs — with ONE real worker the store is
            # exactly the sum of its deltas, so training must match the
            # bare optimizer step for step
            monkeypatch.setattr(bps, "size", lambda: 2)
            x, y = _data(5)
            m = _model(seed=5)
            m_ref = _model(seed=5)
            m.build((None, 8))
            m_ref.build((None, 8))
            for v, vr in zip(m.weights, m_ref.weights):
                v.assign(vr)
            opt = bps.DistributedOptimizer(tf.keras.optimizers.SGD(0.05))
            opt_ref = tf.keras.optimizers.SGD(0.05)
            for _ in range(4):
                with tf.GradientTape() as t:
                    loss = tf.reduce_mean((m(x) - y) ** 2)
                opt.apply_gradients(
                    zip(t.gradient(loss, m.trainable_variables),
                        m.trainable_variables)
                )
                with tf.GradientTape() as tr:
                    loss_r = tf.reduce_mean((m_ref(x) - y) ** 2)
                opt_ref.apply_gradients(
                    zip(tr.gradient(loss_r, m_ref.trainable_variables),
                        m_ref.trainable_variables)
                )
            for v, vr in zip(m.weights, m_ref.weights):
                np.testing.assert_allclose(
                    np.asarray(v), np.asarray(vr), rtol=1e-5, atol=1e-6
                )
            bps.shutdown()
        finally:
            srv.stop()
            sched.stop()


_TF_WORKER_SCRIPT = '''
import os
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import tensorflow as tf
import byteps_tpu.tensorflow as bps

bps.init()
r = int(os.environ["BYTEPS_GLOBAL_RANK"])
# cross-worker average through the TF custom-gradient op
out = bps.push_pull(tf.constant([float(r + 1)] * 8), name="tfmw.g")
assert np.allclose(np.asarray(out), 1.5), out  # (1+2)/2
# and through the optimizer wrap: both workers step by the AVERAGED grad
v = tf.Variable(tf.zeros(4))
opt = bps.DistributedOptimizer(tf.keras.optimizers.SGD(1.0), scope=f"mw")
grad = tf.constant([float(r + 1)] * 4)
opt.apply_gradients([(grad, v)])
assert np.allclose(np.asarray(v), -1.5), np.asarray(v)
bps.shutdown()
print(f"TF_WORKER_{r}_OK")
'''


class TestTFMultiWorker:
    def test_two_workers_average(self, tmp_path):
        """2 TF workers push different gradients; both must apply the
        cross-worker average — the whole plugin stack over the real PS."""
        import os
        import subprocess
        import sys
        import threading

        from byteps_tpu.common.config import Config
        from byteps_tpu.comm.rendezvous import Scheduler
        from byteps_tpu.server.server import PSServer

        sched = Scheduler(num_workers=2, num_servers=1, host="127.0.0.1")
        sched.start()
        env_common = {
            **os.environ,
            "DMLC_PS_ROOT_URI": "127.0.0.1",
            "DMLC_PS_ROOT_PORT": str(sched.port),
            "DMLC_NUM_WORKER": "2",
            "DMLC_NUM_SERVER": "1",
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": "/root/repo",
        }
        scfg = Config.from_env()
        scfg.num_worker = 2
        scfg.num_server = 1
        scfg.ps_root_uri = "127.0.0.1"
        scfg.ps_root_port = sched.port
        srv = PSServer(scfg)
        threading.Thread(target=srv.start, daemon=True).start()
        script = tmp_path / "tf_worker.py"
        script.write_text(_TF_WORKER_SCRIPT)
        procs = [
            subprocess.Popen(
                [sys.executable, str(script)],
                env={**env_common, "BYTEPS_GLOBAL_RANK": str(i)},
                cwd="/root/repo",
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
            for i in range(2)
        ]
        try:
            outs = [p.communicate(timeout=300)[0] for p in procs]
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
            srv.stop()
            sched.stop()
        for i, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"tf worker {i} failed:\n{out}"
        combined = "".join(outs)
        assert "TF_WORKER_0_OK" in combined and "TF_WORKER_1_OK" in combined


class TestTFDistributeStrategy:
    """tf.distribute integration (the reference's MirroredStrategy fork +
    BytepsCrossDeviceOps, mirrored_strategy.py:349-414,
    cross_device_ops.py:585-627 — TF2's cross_device_ops constructor arg
    replaces the fork)."""

    def test_strategy_reduce_single_worker(self):
        from byteps_tpu.tensorflow.distribute import MirroredStrategy

        bps.init()
        strategy = MirroredStrategy(devices=["/cpu:0"])

        with strategy.scope():
            v = tf.Variable(2.0)

        def step():
            return v * 3.0

        per_replica = strategy.run(step)
        out = strategy.reduce(tf.distribute.ReduceOp.SUM, per_replica, axis=None)
        np.testing.assert_allclose(float(out), 6.0)
        bps.shutdown()

    def test_cross_device_ops_route_through_push_pull(self, monkeypatch):
        """The cross-worker hop must be the PS plane: with a fake cluster
        and 1 worker, a SUM reduce through the strategy equals the local
        value (identity through the server), and the PS server must have
        seen CrossDeviceReduce keys."""
        import threading

        from byteps_tpu.common.config import Config
        from byteps_tpu.comm.rendezvous import Scheduler
        from byteps_tpu.server.server import PSServer

        sched = Scheduler(num_workers=1, num_servers=1, host="127.0.0.1")
        sched.start()
        monkeypatch.setenv("DMLC_PS_ROOT_URI", "127.0.0.1")
        monkeypatch.setenv("DMLC_PS_ROOT_PORT", str(sched.port))
        monkeypatch.setenv("DMLC_NUM_WORKER", "1")
        monkeypatch.setenv("DMLC_NUM_SERVER", "1")
        monkeypatch.setenv("BYTEPS_FORCE_DISTRIBUTED", "1")
        srv = PSServer(Config.from_env())
        threading.Thread(target=srv.start, daemon=True).start()
        try:
            from tensorflow.python.distribute.values import PerReplica

            from byteps_tpu.tensorflow.distribute import BytepsCrossDeviceOps

            bps.init()
            # a single-device strategy shortcuts reduces before reaching
            # the ops, so drive the ops directly with a 2-replica value
            ops = BytepsCrossDeviceOps()
            per_replica = PerReplica([tf.constant([1.0, 2.0]), tf.constant([3.0, 4.0])])
            out = ops.reduce(
                tf.distribute.ReduceOp.SUM, per_replica, destinations="/cpu:0"
            )
            # local add_n then PS hop (identity with 1 worker)
            np.testing.assert_allclose(np.asarray(tf.reshape(out, [-1])), [4.0, 6.0])

            # assert on the SERVER's key table: the registry declares
            # names before any network activity, but a server-side entry
            # proves the cross-worker hop actually happened
            from byteps_tpu.common.registry import get_registry

            reduce_keys = {
                c.base_key for c in get_registry().contexts_in_order()
                if "CrossDeviceReduce" in c.name
            }
            assert reduce_keys, "no CrossDeviceReduce tensor was declared"
            served = set()
            for key in srv._keys:
                served.add(key >> 16)  # partition keys carry declared_key<<16
            assert {k >> 16 for k in reduce_keys} & served, (
                "PS server never saw a CrossDeviceReduce key"
            )
            bps.shutdown()
        finally:
            srv.stop()
            sched.stop()


class TestFusedGroup:
    def test_fused_matches_plain_group_mixed_dtypes(self):
        from byteps_tpu.tensorflow.ops import push_pull_group, push_pull_group_fused

        bps.init()
        rng = np.random.default_rng(0)
        ts = [
            tf.constant(rng.normal(size=(5, 7)).astype(np.float32)),
            tf.constant(rng.normal(size=(11,)).astype(np.float32)),
            tf.constant(rng.normal(size=(3, 2)).astype(np.float64)),
            tf.constant(rng.normal(size=(4,)).astype(np.float32)),
        ]
        names = [f"fg.{i}" for i in range(len(ts))]
        plain = push_pull_group(ts, [n + ".p" for n in names], average=False)
        fused = push_pull_group_fused(ts, [n + ".f" for n in names], average=False)
        for p, f, t in zip(plain, fused, ts):
            assert f.shape == t.shape and f.dtype == t.dtype
            np.testing.assert_allclose(np.asarray(p), np.asarray(f), rtol=1e-6)
            np.testing.assert_allclose(np.asarray(f), np.asarray(t), rtol=1e-6)
        bps.shutdown()

    def test_fused_gradient_flows(self):
        from byteps_tpu.tensorflow.ops import push_pull_group_fused

        bps.init()
        x = tf.Variable(tf.ones((3, 3)))
        with tf.GradientTape() as tape:
            (y,) = push_pull_group_fused([x * 2.0], ["fg.grad"], average=False)
            loss = tf.reduce_sum(y * y)
        g = tape.gradient(loss, x)
        np.testing.assert_allclose(np.asarray(g), 8.0 * np.ones((3, 3)), rtol=1e-6)
        bps.shutdown()

    def test_fused_inside_tf_function(self):
        from byteps_tpu.tensorflow.ops import push_pull_group_fused

        bps.init()
        ts = [tf.constant(np.arange(6, dtype=np.float32).reshape(2, 3)),
              tf.constant(np.ones(4, dtype=np.float32))]
        names = ["fgfn.a", "fgfn.b"]

        @tf.function
        def step():
            return push_pull_group_fused(ts, names, average=False)

        for _ in range(2):  # traced call then cached call
            out = step()
            np.testing.assert_allclose(np.asarray(out[0]), np.asarray(ts[0]))
            np.testing.assert_allclose(np.asarray(out[1]), np.asarray(ts[1]))
        bps.shutdown()
