"""TensorFlow plugin tests (byteps/tensorflow parity surface).

Single-worker semantics: push_pull = identity, so DistributedOptimizer /
DistributedGradientTape must train exactly like their bare equivalents —
the reference's test pattern (tests/test_tensorflow_keras.py) with the
torch-plugin test structure."""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

import byteps_tpu.tensorflow as bps


def _data(seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((64, 8)).astype(np.float32)
    y = rng.standard_normal((64, 1)).astype(np.float32)
    return tf.constant(x), tf.constant(y)


def _model(seed=0):
    init = tf.keras.initializers.GlorotUniform(seed=seed)
    return tf.keras.Sequential(
        [
            tf.keras.layers.Dense(16, activation="relu", kernel_initializer=init),
            tf.keras.layers.Dense(1, kernel_initializer=init),
        ]
    )


class TestTFPushPull:
    def test_identity_eager(self):
        bps.init()
        t = tf.range(10, dtype=tf.float32)
        out = bps.push_pull(t, name="tf.t")
        np.testing.assert_allclose(np.asarray(out), np.arange(10, dtype=np.float32))
        bps.shutdown()

    def test_inside_tf_function(self):
        bps.init()

        @tf.function
        def fn(x):
            return bps.push_pull(x, name="tf.fn")

        out = fn(tf.ones(4))
        np.testing.assert_allclose(np.asarray(out), 1.0)
        bps.shutdown()

    def test_gradient_flows_through(self):
        """The registered gradient of push_pull is push_pull of the grad
        (ops.py:136-146): d/dx sum(push_pull(x)) == ones (1 worker)."""
        bps.init()
        x = tf.Variable(tf.ones(5))
        with tf.GradientTape() as tape:
            y = tf.reduce_sum(bps.push_pull(x, name="tf.grad", average=False))
        g = tape.gradient(y, x)
        np.testing.assert_allclose(np.asarray(g), 1.0)
        bps.shutdown()

    def test_fp16_compression_roundtrip(self):
        bps.init()
        t = tf.constant([1.0, 2.5, -3.25], dtype=tf.float32)
        out = bps.push_pull(t, name="tf.fp16", compression=bps.Compression.fp16)
        assert out.dtype == tf.float32
        np.testing.assert_allclose(np.asarray(out), [1.0, 2.5, -3.25])
        bps.shutdown()

    def test_broadcast_identity_single(self):
        bps.init()
        t = tf.constant([3.0, 4.0])
        out = bps.broadcast(t, root_rank=0, name="tf.b")
        np.testing.assert_allclose(np.asarray(out), [3.0, 4.0])
        bps.shutdown()


class TestTFDistributedGradientTape:
    def test_matches_bare_tape(self):
        bps.init()
        x, y = _data()
        m = _model(seed=1)
        m.build((None, 8))
        with tf.GradientTape() as bare:
            loss1 = tf.reduce_mean((m(x) - y) ** 2)
        g1 = bare.gradient(loss1, m.trainable_variables)

        dtape = bps.DistributedGradientTape(tf.GradientTape())
        with dtape:
            loss2 = tf.reduce_mean((m(x) - y) ** 2)
        g2 = dtape.gradient(loss2, m.trainable_variables)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
        bps.shutdown()


class TestTFDistributedOptimizer:
    def test_matches_bare_optimizer(self):
        bps.init()
        x, y = _data(2)
        m1, m2 = _model(seed=2), _model(seed=2)
        m1.build((None, 8))
        m2.build((None, 8))
        for v1, v2 in zip(m2.weights, m1.weights):
            v1.assign(v2)

        opt_ref = tf.keras.optimizers.SGD(0.05)
        opt_dist = bps.DistributedOptimizer(tf.keras.optimizers.SGD(0.05))
        # wrapper keeps the wrapped class's name (load_model contract)
        assert type(opt_dist).__name__ == "SGD"

        for _ in range(5):
            with tf.GradientTape() as t1:
                l1 = tf.reduce_mean((m1(x) - y) ** 2)
            opt_ref.apply_gradients(
                zip(t1.gradient(l1, m1.trainable_variables), m1.trainable_variables)
            )
            with tf.GradientTape() as t2:
                l2 = tf.reduce_mean((m2(x) - y) ** 2)
            opt_dist.apply_gradients(
                zip(t2.gradient(l2, m2.trainable_variables), m2.trainable_variables)
            )
        for p1, p2 in zip(m1.weights, m2.weights):
            np.testing.assert_allclose(
                np.asarray(p1), np.asarray(p2), rtol=1e-5, atol=1e-7
            )
        bps.shutdown()

    def test_model_fit_trains(self):
        """End-to-end keras compile/fit with the wrapped optimizer."""
        bps.init()
        x, y = _data(3)
        m = _model(seed=3)
        m.compile(optimizer=bps.DistributedOptimizer(tf.keras.optimizers.Adam(0.01)),
                  loss="mse")
        h = m.fit(np.asarray(x), np.asarray(y), epochs=3, batch_size=16, verbose=0)
        losses = h.history["loss"]
        assert losses[-1] < losses[0]
        bps.shutdown()

    def test_rejects_non_keras_optimizer(self):
        bps.init()
        with pytest.raises(ValueError, match="keras optimizer"):
            bps.DistributedOptimizer(object())
        bps.shutdown()
