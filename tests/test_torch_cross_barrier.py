"""torch CrossBarrier (torch/cross_barrier.py) — the per-module
pipelined optimizer, reference byteps/torch/cross_barrier.py parity.

Local mode (1 worker ⇒ push_pull identity): training through the
cross-barrier path must match a plain torch SGD trajectory.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import byteps_tpu as bps
from byteps_tpu.torch.cross_barrier import CrossBarrier


def _mlp(seed=0, width=16, depth=3):
    torch.manual_seed(seed)
    layers = []
    for _ in range(depth):
        layers += [torch.nn.Linear(width, width), torch.nn.Tanh()]
    layers.append(torch.nn.Linear(width, 1))
    return torch.nn.Sequential(*layers)


def _batch(seed=1, n=32, width=16):
    g = torch.Generator().manual_seed(seed)
    x = torch.randn(n, width, generator=g)
    y = torch.randn(n, 1, generator=g)
    return x, y


class TestTorchCrossBarrier:
    def test_sgd_trajectory_matches_plain_torch(self):
        """3 steps of cross-barrier SGD == 3 steps of torch.optim.SGD on
        an identical twin model (no barrier in the loop: the next
        forward's pre-hooks supply the per-module waits)."""
        bps.init()
        model = _mlp(seed=7)
        twin = _mlp(seed=7)
        opt = CrossBarrier(model, "sgd", lr=0.05)
        topt = torch.optim.SGD(twin.parameters(), lr=0.05)
        x, y = _batch()
        # the canonical loop: NO zero_grad — _wait zeroes each gradient
        # as it applies it, so nothing accumulates across steps
        for _ in range(3):
            loss = torch.nn.functional.mse_loss(model(x), y)
            loss.backward()

            tloss = torch.nn.functional.mse_loss(twin(x), y)
            topt.zero_grad()
            tloss.backward()
            topt.step()
        opt.step()  # final barrier applies the last backward's updates
        for p, tp in zip(model.parameters(), twin.parameters()):
            np.testing.assert_allclose(
                p.detach().numpy(), tp.detach().numpy(), rtol=1e-5, atol=1e-6
            )
        assert opt.outstanding() == 0
        bps.shutdown()

    def test_momentum_and_loss_decreases(self):
        bps.init()
        model = _mlp(seed=3)
        opt = CrossBarrier(model, "sgd", lr=0.05, momentum=0.9)
        x, y = _batch(seed=5)
        losses = []
        for _ in range(12):
            loss = torch.nn.functional.mse_loss(model(x), y)
            losses.append(float(loss.detach()))
            loss.backward()
        opt.step()
        assert losses[-1] < losses[0] * 0.93, losses  # steady descent
        bps.shutdown()

    def test_forward_prehook_consumes_handles(self):
        """After a backward, handles are outstanding; the next forward
        alone (no step()) must consume every one via the pre-hooks."""
        bps.init()
        model = _mlp(seed=1)
        opt = CrossBarrier(model, "sgd", lr=0.01)
        x, y = _batch(seed=2)
        loss = torch.nn.functional.mse_loss(model(x), y)
        loss.backward()
        assert opt.outstanding() == len(list(model.parameters()))
        model(x)  # forward pre-hooks wait + apply per module
        assert opt.outstanding() == 0
        bps.shutdown()

    def test_adam_runs_and_updates(self):
        bps.init()
        model = _mlp(seed=2)
        before = [p.detach().clone() for p in model.parameters()]
        opt = CrossBarrier(model, "adam", lr=0.01)
        x, y = _batch(seed=3)
        loss = torch.nn.functional.mse_loss(model(x), y)
        loss.backward()
        opt.step()
        changed = [
            not torch.equal(p.detach(), b)
            for p, b in zip(model.parameters(), before)
        ]
        assert all(changed)
        bps.shutdown()

    def test_unknown_optimizer_raises(self):
        with pytest.raises(ValueError, match="unsupported optimizer"):
            CrossBarrier(_mlp(), "lamb")


class TestFifoDiscipline:
    def test_fifo_queue_pops_arrival_order(self):
        from byteps_tpu.common.types import QueueType, TensorTableEntry
        from byteps_tpu.core.scheduler import ScheduledQueue

        q = ScheduledQueue(QueueType.PUSH, discipline="fifo")
        for i, prio in enumerate([0, -5, 3, -1]):
            q.add_task(TensorTableEntry(tensor_name=f"t{i}", key=i, priority=prio))
        got = [q.get_task(timeout=0.1).key for _ in range(4)]
        assert got == [0, 1, 2, 3]  # arrival order, priorities ignored

    def test_priority_queue_pops_priority_order(self):
        from byteps_tpu.common.types import QueueType, TensorTableEntry
        from byteps_tpu.core.scheduler import ScheduledQueue

        q = ScheduledQueue(QueueType.PUSH, discipline="priority")
        for i, prio in enumerate([0, -5, 3, -1]):
            q.add_task(TensorTableEntry(tensor_name=f"t{i}", key=i, priority=prio))
        got = [q.get_task(timeout=0.1).key for _ in range(4)]
        assert got == [2, 0, 3, 1]  # priority desc

    def test_unknown_discipline_raises(self):
        from byteps_tpu.common.types import QueueType
        from byteps_tpu.core.scheduler import ScheduledQueue

        with pytest.raises(ValueError, match="BYTEPS_SCHEDULING"):
            ScheduledQueue(QueueType.PUSH, discipline="lifo")
