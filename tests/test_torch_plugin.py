"""Torch plugin tests (byteps/torch parity surface).

Single-worker semantics: push_pull = identity, so DistributedOptimizer
must train exactly like the bare optimizer (the reference's
test_mxnet-style check applied to torch)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import byteps_tpu.torch as bps


def _model(seed=0):
    torch.manual_seed(seed)
    return torch.nn.Sequential(
        torch.nn.Linear(8, 16), torch.nn.ReLU(), torch.nn.Linear(16, 1)
    )


def _data(seed=0):
    g = torch.Generator().manual_seed(seed)
    x = torch.randn(64, 8, generator=g)
    y = torch.randn(64, 1, generator=g)
    return x, y


class TestTorchPushPull:
    def test_identity(self):
        bps.init()
        t = torch.arange(10, dtype=torch.float32)
        out = bps.push_pull(t, name="torch.t")
        assert torch.allclose(out, t)
        bps.shutdown()

    def test_inplace(self):
        bps.init()
        t = torch.ones(5)
        ret = bps.push_pull_inplace(t, name="torch.ip")
        assert ret is t and torch.allclose(t, torch.ones(5))
        bps.shutdown()

    def test_async_poll(self):
        bps.init()
        h = bps.push_pull_async(torch.ones(3), name="torch.async")
        assert bps.poll(h)
        assert torch.allclose(bps.synchronize(h), torch.ones(3))
        bps.shutdown()

    def test_name_required(self):
        bps.init()
        with pytest.raises(ValueError, match="name"):
            bps.push_pull_async(torch.ones(2))
        bps.shutdown()


class TestTorchDistributedOptimizer:
    def test_matches_bare_optimizer(self):
        bps.init()
        m1, m2 = _model(), _model()
        m2.load_state_dict(m1.state_dict())
        x, y = _data()

        opt_ref = torch.optim.SGD(m1.parameters(), lr=0.05)
        opt_dist = bps.DistributedOptimizer(
            torch.optim.SGD(m2.parameters(), lr=0.05),
            named_parameters=m2.named_parameters(),
        )
        for _ in range(5):
            opt_ref.zero_grad()
            torch.nn.functional.mse_loss(m1(x), y).backward()
            opt_ref.step()

            opt_dist.zero_grad()
            torch.nn.functional.mse_loss(m2(x), y).backward()
            opt_dist.step()

        for p1, p2 in zip(m1.parameters(), m2.parameters()):
            assert torch.allclose(p1, p2, rtol=1e-5, atol=1e-7)
        bps.shutdown()

    def test_backward_passes_per_step(self):
        bps.init()
        m = _model()
        x, y = _data()
        opt = bps.DistributedOptimizer(
            torch.optim.SGD(m.parameters(), lr=0.05),
            named_parameters=m.named_parameters(),
            backward_passes_per_step=2,
        )
        p0 = [p.clone() for p in m.parameters()]
        torch.nn.functional.mse_loss(m(x), y).backward()
        assert opt.step() is None  # first pass: accumulate, no step
        for p, q in zip(m.parameters(), p0):
            assert torch.allclose(p, q)
        torch.nn.functional.mse_loss(m(x), y).backward()
        opt.step()  # second pass: communicate + step
        changed = any(
            not torch.allclose(p, q) for p, q in zip(m.parameters(), p0)
        )
        assert changed
        bps.shutdown()

    def test_duplicate_names_rejected(self):
        bps.init()
        m = _model()
        with pytest.raises(ValueError, match="duplicate"):
            bps.DistributedOptimizer(
                torch.optim.SGD(m.parameters(), lr=0.1),
                named_parameters=[("same", p) for p in m.parameters()],
            )
        bps.shutdown()


class TestTorchBroadcast:
    def test_broadcast_parameters_noop_single(self):
        bps.init()
        m = _model()
        before = [p.clone() for p in m.parameters()]
        bps.broadcast_parameters(m.state_dict(), root_rank=0)
        for p, q in zip(m.parameters(), before):
            assert torch.allclose(p, q)
        bps.shutdown()

    def test_broadcast_optimizer_state(self):
        bps.init()
        m = _model()
        opt = torch.optim.Adam(m.parameters(), lr=1e-3)
        torch.nn.functional.mse_loss(m(torch.randn(4, 8)), torch.randn(4, 1)).backward()
        opt.step()
        bps.broadcast_optimizer_state(opt, root_rank=0)  # must round-trip
        assert len(opt.state) > 0
        bps.shutdown()


class TestMixedPrecision:
    def test_dynamic_loss_scale_skips_overflow(self):
        import jax.numpy as jnp
        import optax

        from byteps_tpu.mixed_precision import dynamic_loss_scale

        tx = dynamic_loss_scale(optax.sgd(0.1), init_scale=4.0)
        params = {"w": jnp.ones(4)}
        st = tx.init(params)
        # clean step: grads scaled by 4 → unscaled to 1 → update −0.1
        up, st = tx.update({"w": jnp.full(4, 4.0)}, st, params)
        np.testing.assert_allclose(np.asarray(up["w"]), -0.1, rtol=1e-6)
        assert float(st.scale) == 4.0
        # overflow: update zeroed, scale halves
        up, st = tx.update({"w": jnp.full(4, np.inf)}, st, params)
        np.testing.assert_allclose(np.asarray(up["w"]), 0.0)
        assert float(st.scale) == 2.0

    def test_master_weights_bf16(self):
        import jax.numpy as jnp
        import optax

        from byteps_tpu.mixed_precision import master_weights

        tx = master_weights(optax.sgd(0.01))
        params = {"w": jnp.ones(64, jnp.bfloat16)}
        st = tx.init(params)
        assert st.masters["w"].dtype == jnp.float32
        # tiny updates accumulate in the fp32 master even when each is
        # below bf16 resolution around 1.0
        g = {"w": jnp.full(64, 0.01, jnp.bfloat16)}
        p = params
        for _ in range(10):
            up, st = tx.update(g, st, p)
            p = optax.apply_updates(p, up)
        np.testing.assert_allclose(
            np.asarray(st.masters["w"]), 1.0 - 10 * 0.01 * 0.01, rtol=1e-3
        )


class TestTorchDDP:
    def test_ddp_matches_bare_training(self):
        bps.init()
        from byteps_tpu.torch.parallel import DistributedDataParallel

        m1, m2 = _model(seed=3), _model(seed=3)
        m2.load_state_dict(m1.state_dict())
        ddp = DistributedDataParallel(m2, bucket_bytes=64)  # forces >1 bucket
        assert len(ddp._buckets) > 1
        x, y = _data(seed=3)
        o1 = torch.optim.SGD(m1.parameters(), lr=0.05)
        o2 = torch.optim.SGD(m2.parameters(), lr=0.05)
        for _ in range(5):
            o1.zero_grad()
            torch.nn.functional.mse_loss(m1(x), y).backward()
            o1.step()
            o2.zero_grad()
            torch.nn.functional.mse_loss(ddp(x), y).backward()
            ddp.grad_sync()
            o2.step()
        for p1, p2 in zip(m1.parameters(), m2.parameters()):
            assert torch.allclose(p1, p2, rtol=1e-5, atol=1e-7)
        bps.shutdown()

    def test_no_sync_accumulation(self):
        bps.init()
        from byteps_tpu.torch.parallel import DistributedDataParallel

        m = _model(seed=4)
        ddp = DistributedDataParallel(m)
        x, y = _data(seed=4)
        with ddp.no_sync():
            torch.nn.functional.mse_loss(ddp(x), y).backward()
        assert ddp._handles == []  # nothing communicated
        torch.nn.functional.mse_loss(ddp(x), y).backward()
        ddp.grad_sync()  # second (sync) pass communicates
        bps.shutdown()


class TestCompressionParams:
    def test_translation(self):
        from byteps_tpu.compression.registry import (
            create_compressor,
            translate_compression_params,
        )

        kw = translate_compression_params(
            {"compressor": "randomk", "k": 0.1, "ef": "vanilla",
             "momentum": "nesterov", "momentum_mu": 0.8, "seed": 9}
        )
        assert kw["byteps_compressor_type"] == "randomk"
        assert kw["byteps_compressor_k"] == "0.1"
        assert kw["byteps_ef_type"] == "vanilla"
        c = create_compressor(kw, 1000)
        from byteps_tpu.compression.momentum import NesterovMomentum

        assert isinstance(c, NesterovMomentum) and c.mu == 0.8

    def test_torch_optimizer_declares_compression(self):
        bps.init()
        from byteps_tpu.common.registry import get_registry

        m = _model(seed=7)
        bps.DistributedOptimizer(
            torch.optim.SGD(m.parameters(), lr=0.1),
            named_parameters=m.named_parameters(),
            compression_params={"compressor": "topk", "k": 0.5},
        )
        ctx = get_registry().get("Gradient.0.weight")
        assert ctx.kwargs["byteps_compressor_type"] == "topk"
        bps.shutdown()
