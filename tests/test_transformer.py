"""Flagship transformer tests: the 4-D-parallel (dp, pp, sp, tp) train step
must match single-device training numerically, and each parallel dimension
is exercised on the 8-device virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from byteps_tpu.models.transformer import (
    TransformerConfig,
    build_forward,
    build_train_step,
    init_params,
    shard_params,
    tiny_test,
)
from byteps_tpu.parallel.mesh_utils import factorize_mesh, make_training_mesh
from byteps_tpu.parallel.ring_attention import ring_attention


def _mesh(dp=1, pp=1, sp=1, tp=1):
    return make_training_mesh(
        n_devices=dp * pp * sp * tp,
        axis_sizes={"dp": dp, "pp": pp, "sp": sp, "tp": tp},
    )


def _data(cfg, batch=4, seed=0):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, cfg.vocab_size, size=(batch, cfg.max_seq)).astype(np.int32)
    targets = np.roll(tokens, -1, axis=1).astype(np.int32)
    return jnp.asarray(tokens), jnp.asarray(targets)


def _run_steps(cfg, mesh, n_steps=3, batch=4, lr=0.1, seed=0):
    params = shard_params(init_params(cfg, seed=seed, pp_size=mesh.shape.get("pp", 1)), cfg, mesh)
    tx = optax.sgd(lr)
    opt_state = jax.jit(tx.init)(params)
    step = build_train_step(cfg, mesh, tx, donate=False)
    tokens, targets = _data(cfg, batch=batch)
    losses = []
    for _ in range(n_steps):
        params, opt_state, loss = step(params, opt_state, tokens, targets)
        losses.append(float(loss))
    return losses, params


class TestMeshFactorization:
    def test_factorize_default_is_pure_dp(self):
        # a data-parallel framework's default mesh is all-dp (VERDICT r3 #7)
        assert factorize_mesh(8) == {"dp": 8}
        assert factorize_mesh(1) == {"dp": 1}

    def test_factorize_multi_axis(self):
        want = ("dp", "tp", "sp", "pp")
        assert factorize_mesh(8, want) == {"dp": 2, "tp": 2, "sp": 2, "pp": 1}
        assert factorize_mesh(16, want) == {"dp": 2, "tp": 2, "sp": 2, "pp": 2}
        assert factorize_mesh(4, want) == {"dp": 2, "tp": 2, "sp": 1, "pp": 1}

    def test_default_training_mesh_is_dp(self):
        import jax

        from byteps_tpu.parallel.mesh_utils import make_training_mesh

        n = len(jax.devices())
        mesh = make_training_mesh()
        assert mesh.shape["dp"] == n
        assert mesh.shape["tp"] == mesh.shape["pp"] == mesh.shape["sp"] == 1


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, causal):
        """Ring attention over sp=4 must equal dense attention on the full
        sequence."""
        rng = np.random.default_rng(0)
        B, H, S, dh, sp = 2, 2, 16, 8, 4
        q = rng.normal(size=(B, H, S, dh)).astype(np.float32)
        k = rng.normal(size=(B, H, S, dh)).astype(np.float32)
        v = rng.normal(size=(B, H, S, dh)).astype(np.float32)

        # dense reference
        scores = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(dh)
        if causal:
            mask = np.tril(np.ones((S, S), bool))
            scores = np.where(mask, scores, -1e30)
        p = np.exp(scores - scores.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        ref = np.einsum("bhqk,bhkd->bhqd", p, v)

        mesh = Mesh(np.array(jax.devices()[:sp]).reshape(sp), ("sp",))

        def body(qb, kb, vb):
            return ring_attention(qb, kb, vb, "sp", sp, causal=causal)

        fn = jax.jit(
            jax.shard_map(
                body, mesh=mesh,
                in_specs=(P(None, None, "sp"),) * 3,
                out_specs=P(None, None, "sp"),
                check_vma=False,
            )
        )
        out = fn(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-5)

    def test_differentiable(self):
        sp = 2
        mesh = Mesh(np.array(jax.devices()[:sp]).reshape(sp), ("sp",))
        rng = np.random.default_rng(1)
        q = jnp.asarray(rng.normal(size=(1, 2, 8, 4)).astype(np.float32))

        def loss(qb):
            out = ring_attention(qb, qb, qb, "sp", sp, causal=True)
            return jnp.sum(out**2)

        def body(qb):
            l, g = jax.value_and_grad(loss)(qb)
            return jax.lax.psum(l, "sp"), g

        fn = jax.jit(
            jax.shard_map(
                body, mesh=mesh,
                in_specs=(P(None, None, "sp"),),
                out_specs=(P(), P(None, None, "sp")),
                check_vma=False,
            )
        )
        l, g = fn(q)
        assert np.isfinite(float(l))
        assert np.all(np.isfinite(np.asarray(g)))


class TestParallelEquivalence:
    def test_dp8_matches_single(self):
        cfg = tiny_test()
        l1, _ = _run_steps(cfg, _mesh(dp=1), batch=8)
        l8, _ = _run_steps(cfg, _mesh(dp=8), batch=8)
        np.testing.assert_allclose(l1, l8, rtol=1e-4)

    def test_pp2_matches_single(self):
        cfg = tiny_test()
        l1, _ = _run_steps(cfg, _mesh(pp=1), batch=4)
        l2, _ = _run_steps(cfg, _mesh(pp=2), batch=4)
        np.testing.assert_allclose(l1, l2, rtol=1e-4)

    def test_sp2_matches_single(self):
        cfg = tiny_test(causal=True)
        l1, _ = _run_steps(cfg, _mesh(sp=1), batch=4)
        l2, _ = _run_steps(cfg, _mesh(sp=2), batch=4)
        np.testing.assert_allclose(l1, l2, rtol=1e-3)

    def test_tp2_matches_single(self):
        cfg = tiny_test()
        l1, _ = _run_steps(cfg, _mesh(tp=1), batch=4)
        l2, _ = _run_steps(cfg, _mesh(tp=2), batch=4)
        np.testing.assert_allclose(l1, l2, rtol=1e-3)

    def test_full_4d_mesh_trains(self):
        """dp×pp×sp×tp = 1×2×2×2 (8 devices): loss matches single device and
        decreases."""
        cfg = tiny_test(causal=True)
        l1, _ = _run_steps(cfg, _mesh(), n_steps=5, batch=4)
        l8, _ = _run_steps(cfg, _mesh(pp=2, sp=2, tp=2), n_steps=5, batch=4)
        np.testing.assert_allclose(l1, l8, rtol=2e-3)
        assert l8[-1] < l8[0]

    @pytest.mark.parametrize("axes", [
        {"pp": 2}, {"sp": 2}, {"tp": 2}, {"pp": 2, "tp": 2}, {"sp": 2, "tp": 2},
    ])
    def test_dp2_composed_matches_single(self, axes):
        """dp=2 composed with every other axis (the round-1 advisor bug
        class lived exactly in dp>1 × another axis): train-step losses
        must match the single-device run."""
        cfg = tiny_test(causal=True)
        l1, _ = _run_steps(cfg, _mesh(), n_steps=3, batch=4)
        ln, _ = _run_steps(cfg, _mesh(dp=2, **axes), n_steps=3, batch=4)
        np.testing.assert_allclose(l1, ln, rtol=2e-3)

    @pytest.mark.parametrize("axes", [
        {"pp": 2}, {"sp": 2}, {"tp": 2}, {"pp": 2, "sp": 2}, {"pp": 2, "tp": 2},
    ])
    def test_dp2_composed_cached_decode_matches_single(self, axes):
        """dp=2 × each other axis: microbatched KV-cached decode must emit
        the same tokens as the single-device decoder."""
        from byteps_tpu.models.transformer import build_generate_cached

        cfg = tiny_test(causal=True, microbatches=2)
        prompt = np.array(
            [[1, 2, 3], [4, 5, 6], [7, 8, 9], [3, 1, 2]], np.int32
        )
        p1 = shard_params(init_params(cfg, seed=3), cfg, _mesh())
        g1 = build_generate_cached(cfg, _mesh())(p1, prompt, n_new=5)
        meshn = _mesh(dp=2, **axes)
        pn = shard_params(
            init_params(cfg, seed=3, pp_size=axes.get("pp", 1)), cfg, meshn
        )
        gn = build_generate_cached(cfg, meshn)(pn, prompt, n_new=5)
        np.testing.assert_array_equal(g1, gn)


class TestMoE:
    def test_moe_trains_with_expert_parallel(self):
        """MoE layer with experts sharded over the sp axis (ep reuse):
        all_to_all dispatch must compile and the model must train."""
        cfg = tiny_test(moe=True, n_experts=4, causal=True)
        losses, _ = _run_steps(cfg, _mesh(sp=2), n_steps=6, batch=4, lr=0.05)
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]

    def test_moe_single_device(self):
        cfg = tiny_test(moe=True, n_experts=4)
        losses, _ = _run_steps(cfg, _mesh(), n_steps=6, batch=4, lr=0.05)
        assert losses[-1] < losses[0]

    def test_moe_top1_still_supported(self):
        cfg = tiny_test(moe=True, n_experts=4, moe_top_k=1, causal=True)
        losses, _ = _run_steps(cfg, _mesh(sp=2), n_steps=6, batch=4, lr=0.05)
        assert all(np.isfinite(losses)) and losses[-1] < losses[0]

    def test_moe_top2_full_capacity_is_gate_mixture(self):
        """With E=2 experts and top_k=2 at no-drop capacity, every token
        visits both experts and the output must equal the softmax-gated
        mixture of the two expert MLPs (renormalized top-2 gates over 2
        experts == the full softmax)."""
        import jax.numpy as jnp

        from byteps_tpu.parallel.moe import moe_mlp

        rng = np.random.default_rng(7)
        t, d, f, e = 10, 6, 12, 2
        x = jnp.asarray(rng.normal(size=(t, d)), jnp.float32)
        router = jnp.asarray(rng.normal(size=(d, e)), jnp.float32)
        w1 = jnp.asarray(rng.normal(size=(e, d, f)) * 0.3, jnp.float32)
        b1 = jnp.asarray(rng.normal(size=(e, f)) * 0.1, jnp.float32)
        w2 = jnp.asarray(rng.normal(size=(e, f, d)) * 0.3, jnp.float32)
        b2 = jnp.asarray(rng.normal(size=(e, d)) * 0.1, jnp.float32)

        y = moe_mlp(
            x, router, w1, b1, w2, b2, axis_name=None, axis_size=1,
            capacity_factor=float(e), top_k=2,
        )

        gates = np.asarray(jax.nn.softmax(x @ router, axis=-1))
        expect = np.zeros((t, d), np.float32)
        for ei in range(e):
            h = np.asarray(jax.nn.gelu(x @ w1[ei] + b1[ei]))
            out = h @ np.asarray(w2[ei]) + np.asarray(b2[ei])
            expect += gates[:, ei : ei + 1] * out
        np.testing.assert_allclose(np.asarray(y), expect, rtol=1e-4, atol=1e-4)

    def test_moe_bf16_positions_exact_past_256(self):
        """Queue positions must be exact beyond 256 even when the compute
        dtype is bfloat16 (a bf16 cumsum saturates at 256 — collided
        slots would silently blend tokens)."""
        from byteps_tpu.parallel.moe import moe_mlp

        rng = np.random.default_rng(3)
        t, d, f, e = 320, 4, 8, 2
        x32 = jnp.asarray(rng.normal(size=(t, d)), jnp.float32)
        router = jnp.asarray(rng.normal(size=(d, e)), jnp.float32)
        w1 = jnp.asarray(rng.normal(size=(e, d, f)) * 0.2, jnp.float32)
        b1 = jnp.zeros((e, f), jnp.float32)
        w2 = jnp.asarray(rng.normal(size=(e, f, d)) * 0.2, jnp.float32)
        b2 = jnp.zeros((e, d), jnp.float32)

        def run(dt):
            return np.asarray(
                moe_mlp(
                    x32.astype(dt), router.astype(dt), w1.astype(dt),
                    b1.astype(dt), w2.astype(dt), b2.astype(dt),
                    axis_name=None, axis_size=1,
                    capacity_factor=float(e), top_k=2,
                )
            ).astype(np.float32)

        y32, y16 = run(jnp.float32), run(jnp.bfloat16)
        # bf16 arithmetic error is small per element; slot collisions
        # (wrongly blended tokens) would blow far past this tolerance
        np.testing.assert_allclose(y16, y32, rtol=0.15, atol=0.05)

    def test_moe_top2_respects_capacity(self):
        """Overflowing tokens of a saturated expert are dropped, never
        written past the expert's queue (static shapes)."""
        from byteps_tpu.parallel.moe import moe_mlp

        rng = np.random.default_rng(0)
        t, d, f, e = 16, 4, 8, 4
        # router biased so one expert wins for every token
        router = np.zeros((d, e), np.float32)
        router[:, 0] = 10.0
        x = jnp.asarray(np.abs(rng.normal(size=(t, d))), jnp.float32)
        w1 = jnp.asarray(rng.normal(size=(e, d, f)) * 0.3, jnp.float32)
        b1 = jnp.zeros((e, f), jnp.float32)
        w2 = jnp.asarray(rng.normal(size=(e, f, d)) * 0.3, jnp.float32)
        b2 = jnp.zeros((e, d), jnp.float32)
        y = moe_mlp(
            x, jnp.asarray(router), w1, b1, w2, b2, axis_name=None,
            axis_size=1, capacity_factor=0.5, top_k=2,
        )
        assert np.isfinite(np.asarray(y)).all()

    def test_moe_cached_decode_matches_single(self):
        """KV-cached decode with MoE: experts sharded over sp, layers over
        pp, batch over dp — tokens must match the single-device cached
        decoder.  Both prefill and per-token steps default to no-drop
        serving capacity (prefill_capacity_factor=None), so cross-mesh
        parity holds unconditionally — no expert-overflow caveat."""
        from byteps_tpu.models.transformer import build_generate_cached

        cfg = tiny_test(moe=True, n_experts=4, causal=True)
        prompt = np.array(
            [[1, 2, 3], [4, 5, 6], [7, 8, 9], [3, 1, 2]], np.int32
        )
        p1 = shard_params(init_params(cfg, seed=3), cfg, _mesh())
        g1 = build_generate_cached(cfg, _mesh())(p1, prompt, n_new=5)
        mesh8 = _mesh(dp=2, pp=2, sp=2)
        p8 = shard_params(init_params(cfg, seed=3, pp_size=2), cfg, mesh8)
        g8 = build_generate_cached(cfg, mesh8)(p8, prompt, n_new=5)
        np.testing.assert_array_equal(g1, g8)


class TestForward:
    def test_forward_shapes(self):
        cfg = tiny_test()
        mesh = _mesh()
        params = shard_params(init_params(cfg), cfg, mesh)
        fwd = build_forward(cfg, mesh)
        tokens, _ = _data(cfg, batch=4)
        logits = fwd(params, tokens)
        # (M=pp=1 microbatch, B, S, V)
        assert logits.shape == (1, 4, cfg.max_seq, cfg.vocab_size)


class TestMaskedLoss:
    def test_ignore_index_positions_excluded(self):
        """target < 0 positions (MLM unmasked / padding) must not affect
        the loss: masking half the targets equals computing the mean over
        only the kept positions."""
        cfg = tiny_test()
        mesh = _mesh()
        params = shard_params(init_params(cfg), cfg, mesh)
        import optax

        from byteps_tpu.models.transformer import _local_loss
        from jax.sharding import PartitionSpec as P

        tokens, targets = _data(cfg, batch=4)
        t_np = np.asarray(targets)
        masked = t_np.copy()
        masked[:, ::2] = -1  # ignore every other position

        def loss_of(tgt):
            fn = jax.jit(
                jax.shard_map(
                    lambda p, tok, tg: _local_loss(cfg, mesh, p, tok, tg),
                    mesh=mesh,
                    in_specs=(
                        __import__("byteps_tpu.models.transformer", fromlist=["param_specs"]).param_specs(cfg),
                        P("dp", "sp"), P("dp", "sp"),
                    ),
                    out_specs=P(),
                    check_vma=True,
                )
            )
            return fn(params, tokens, jnp.asarray(tgt))

        full = float(loss_of(t_np))
        half = float(loss_of(masked))
        # independent check: recompute the expected masked mean from logits
        fwd = build_forward(cfg, mesh)
        logits = np.asarray(fwd(params, tokens))[0].astype(np.float64)
        logz = np.log(np.exp(logits - logits.max(-1, keepdims=True)).sum(-1)) + logits.max(-1).reshape(*logits.shape[:-1])
        rows = np.take_along_axis(logits, np.maximum(t_np, 0)[..., None], axis=-1)[..., 0]
        tok_loss = logz - rows
        keep = masked >= 0
        expected = tok_loss[keep].mean()
        np.testing.assert_allclose(half, expected, rtol=1e-4)
        assert abs(full - half) > 1e-6  # masking actually changes the value


class TestUlyssesAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, causal):
        """Ulysses all-to-all attention over sp=4 must equal dense
        attention on the full sequence."""
        from byteps_tpu.parallel.ulysses import ulysses_attention

        B, H, S, dh, sp = 2, 4, 16, 8, 4
        rng = np.random.default_rng(0)
        q, k, v = (
            jnp.asarray(rng.normal(size=(B, H, S, dh)).astype(np.float32))
            for _ in range(3)
        )
        ref = np.asarray(ulysses_attention(q, k, v, None, 1, causal=causal))

        mesh = Mesh(np.array(jax.devices()[:sp]).reshape(sp), ("sp",))

        def body(qb, kb, vb):
            return ulysses_attention(qb, kb, vb, "sp", sp, causal=causal)

        out = jax.jit(
            jax.shard_map(
                body, mesh=mesh,
                in_specs=(P(None, None, "sp"),) * 3,
                out_specs=P(None, None, "sp"),
            )
        )(q, k, v)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)

    def test_rejects_indivisible_heads(self):
        from byteps_tpu.parallel.ulysses import ulysses_attention

        mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("sp",))
        q = jnp.zeros((1, 2, 16, 8))  # 2 heads, sp=4 → refuse

        def body(qb):
            return ulysses_attention(qb, qb, qb, "sp", 4, causal=False)

        with pytest.raises(ValueError, match="divisible"):
            jax.jit(
                jax.shard_map(
                    body, mesh=mesh,
                    in_specs=(P(None, None, "sp"),),
                    out_specs=P(None, None, "sp"),
                )
            )(q)

    def test_sp2_ulysses_train_step_matches_single(self):
        """The full transformer train step with seq_parallel_impl='ulysses'
        must match the single-device loss."""
        cfg = tiny_test(causal=True, seq_parallel_impl="ulysses")
        l1, _ = _run_steps(cfg, _mesh(sp=1), batch=4)
        l2, _ = _run_steps(cfg, _mesh(sp=2), batch=4)
        np.testing.assert_allclose(l1, l2, rtol=1e-3)


class TestRingFlashAttention:
    """Ring attention with Pallas flash hops (round-2 VERDICT #9): must be
    numerically identical to the dense ring, differentiable, and must not
    materialize block-pair score matrices at the jaxpr level."""

    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense_ring(self, causal):
        from byteps_tpu.parallel.ring_attention import ring_flash_attention

        rng = np.random.default_rng(0)
        B, H, S, dh, sp = 2, 2, 64, 8, 4
        q = rng.normal(size=(B, H, S, dh)).astype(np.float32)
        k = rng.normal(size=(B, H, S, dh)).astype(np.float32)
        v = rng.normal(size=(B, H, S, dh)).astype(np.float32)

        scores = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(dh)
        if causal:
            mask = np.tril(np.ones((S, S), bool))
            scores = np.where(mask, scores, -1e30)
        p = np.exp(scores - scores.max(-1, keepdims=True))
        ref = np.einsum("bhqk,bhkd->bhqd", p / p.sum(-1, keepdims=True), v)

        mesh = Mesh(np.array(jax.devices()[:sp]).reshape(sp), ("sp",))

        def body(qb, kb, vb):
            return ring_flash_attention(
                qb, kb, vb, "sp", sp, causal=causal,
                block_q=8, block_k=8, interpret=True,
            )

        fn = jax.jit(
            jax.shard_map(
                body, mesh=mesh,
                in_specs=(P(None, None, "sp"),) * 3,
                out_specs=P(None, None, "sp"),
                check_vma=False,
            )
        )
        out = fn(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-5)

    def test_differentiable_matches_dense_ring_grad(self):
        from byteps_tpu.parallel.ring_attention import (
            ring_attention,
            ring_flash_attention,
        )

        sp = 2
        mesh = Mesh(np.array(jax.devices()[:sp]).reshape(sp), ("sp",))
        rng = np.random.default_rng(1)
        q = jnp.asarray(rng.normal(size=(1, 2, 32, 8)).astype(np.float32))

        def make_loss(fn):
            def loss(qb):
                out = fn(qb)
                return jnp.sum(out**2)

            def body(qb):
                l, g = jax.value_and_grad(loss)(qb)
                return jax.lax.psum(l, "sp"), g

            return jax.jit(
                jax.shard_map(
                    body, mesh=mesh,
                    in_specs=(P(None, None, "sp"),),
                    out_specs=(P(), P(None, None, "sp")),
                    check_vma=False,
                )
            )

        l1, g1 = make_loss(
            lambda qb: ring_attention(qb, qb, qb, "sp", sp, causal=True)
        )(q)
        l2, g2 = make_loss(
            lambda qb: ring_flash_attention(
                qb, qb, qb, "sp", sp, causal=True,
                block_q=8, block_k=8, interpret=True,
            )
        )(q)
        np.testing.assert_allclose(float(l1), float(l2), rtol=1e-4)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   rtol=2e-3, atol=2e-4)

    def test_no_dense_score_matrix_in_jaxpr(self):
        """Peak-memory proxy: the flash ring's jaxpr must contain NO
        intermediate of shape (..., S_local, S_local) — the dense ring's
        per-hop score matrix.  Blocks are 8×8 inside the kernel, so any
        32×32 array would mean dense materialization leaked back in."""
        from byteps_tpu.parallel.ring_attention import (
            ring_attention,
            ring_flash_attention,
        )

        sp = 2
        S_local = 32
        mesh = Mesh(np.array(jax.devices()[:sp]).reshape(sp), ("sp",))

        def wrap(fn):
            return jax.shard_map(
                fn, mesh=mesh,
                in_specs=(P(None, None, "sp"),) * 3,
                out_specs=P(None, None, "sp"),
                check_vma=False,
            )

        q = jnp.zeros((1, 2, S_local * sp, 8), jnp.float32)

        def has_square(fn):
            jaxpr = jax.make_jaxpr(wrap(fn))(q, q, q)
            found = []

            def subjaxprs_of(params):
                for val in params.values():
                    if isinstance(val, jax.extend.core.ClosedJaxpr):
                        yield val.jaxpr
                    elif isinstance(val, jax.extend.core.Jaxpr):
                        yield val
                    elif isinstance(val, (tuple, list)):
                        for item in val:
                            if isinstance(item, jax.extend.core.ClosedJaxpr):
                                yield item.jaxpr
                            elif isinstance(item, jax.extend.core.Jaxpr):
                                yield item

            def scan_eqns(jx):
                for eqn in jx.eqns:
                    for var in eqn.outvars:
                        shape = getattr(getattr(var, "aval", None), "shape", ())
                        if len(shape) >= 2 and shape[-1] == S_local and shape[-2] == S_local:
                            found.append(shape)
                    for sub in subjaxprs_of(eqn.params):
                        scan_eqns(sub)

            scan_eqns(jaxpr.jaxpr)
            return bool(found)

        dense_fn = lambda a, b, c: ring_attention(a, b, c, "sp", sp, causal=True)
        flash_fn = lambda a, b, c: ring_flash_attention(
            a, b, c, "sp", sp, causal=True, block_q=8, block_k=8, interpret=True
        )
        assert has_square(dense_fn), "sanity: dense ring materializes scores"
        assert not has_square(flash_fn), "flash ring leaked a dense score matrix"

    def test_model_sp2_with_flash_ring_trains(self):
        """Model wiring: use_flash + sp>1 routes through ring_flash_attention
        (dense fallback off-TPU) and matches the plain ring numerically."""
        cfg_d = tiny_test(causal=True)
        cfg_f = tiny_test(causal=True, use_flash=True)
        l1, _ = _run_steps(cfg_d, _mesh(sp=2), batch=4)
        l2, _ = _run_steps(cfg_f, _mesh(sp=2), batch=4)
        np.testing.assert_allclose(l1, l2, rtol=1e-3)


class TestGQA:
    """Grouped-query attention (n_kv_heads < n_heads): KV projections and
    the decode cache carry only the KV groups; query heads share them."""

    def test_param_shapes_and_validation(self):
        cfg = tiny_test(n_heads=4, n_kv_heads=2)
        p = init_params(cfg)
        assert p["wk"].shape[-2] == 2 and p["wq"].shape[-2] == 4
        with pytest.raises(ValueError, match="n_kv_heads"):
            tiny_test(n_heads=4, n_kv_heads=3)

    def test_tied_weights_match_mha_forward(self):
        """Expanding each KV group across its query heads must reproduce
        classic MHA exactly in the FORWARD pass (training steps diverge
        by design after one update: GQA's wk gradient sums over the
        group's query heads, MHA updates each copy independently)."""
        cfg_g = tiny_test(n_heads=4, n_kv_heads=2, causal=True)
        cfg_m = tiny_test(n_heads=4, causal=True)
        pg = init_params(cfg_g, seed=1)
        pm = {k: v.copy() for k, v in pg.items()}
        pm["wk"] = np.repeat(pg["wk"], 2, axis=-2)
        pm["wv"] = np.repeat(pg["wv"], 2, axis=-2)
        mesh = _mesh()
        tokens, _ = _data(cfg_g, batch=4)
        lg = build_forward(cfg_g, mesh)(shard_params(pg, cfg_g, mesh), tokens)
        lm = build_forward(cfg_m, mesh)(shard_params(pm, cfg_m, mesh), tokens)
        np.testing.assert_allclose(
            np.asarray(lg), np.asarray(lm), rtol=1e-5, atol=1e-5
        )

    def test_gqa_trains_on_composed_mesh(self):
        """dp2 × tp2: KV heads shard over tp (kv_local = 1)."""
        cfg = tiny_test(n_heads=4, n_kv_heads=2, causal=True)
        losses, _ = _run_steps(cfg, _mesh(dp=2, tp=2), batch=4)
        assert np.isfinite(losses).all() and losses[-1] < losses[0]

    def test_gqa_cached_decode_matches_single(self):
        """KV-cached decode with the grouped (small-cache) attend emits
        the same tokens on a composed mesh as single-device."""
        from byteps_tpu.models.transformer import build_generate_cached

        cfg = tiny_test(n_heads=4, n_kv_heads=2, causal=True, microbatches=2)
        prompt = np.array(
            [[1, 2, 3], [4, 5, 6], [7, 8, 9], [3, 1, 2]], np.int32
        )
        p1 = shard_params(init_params(cfg, seed=3), cfg, _mesh())
        g1 = build_generate_cached(cfg, _mesh())(p1, prompt, n_new=5)
        meshn = _mesh(dp=2, tp=2)
        pn = shard_params(init_params(cfg, seed=3), cfg, meshn)
        gn = build_generate_cached(cfg, meshn)(pn, prompt, n_new=5)
        np.testing.assert_array_equal(g1, gn)

    def test_gqa_cache_is_smaller(self):
        """The decode cache allocates n_kv_heads, not n_heads — the GQA
        serving-memory win, asserted structurally via the kv-local head
        count the decoder reads from wk."""
        cfg = tiny_test(n_heads=4, n_kv_heads=2, causal=True)
        p = init_params(cfg)
        assert p["wk"].shape[-2] == cfg.kv_heads == 2
