"""ThreadSanitizer smoke for the key-striped native engine (ISSUE 7).

The striped reducer plane moved the C++ server from "one lock around
everything" to per-stripe shard locks, a lock-free task ring per
stripe, and an atomic-countdown fused gather — exactly the kind of
concurrency that wants a race detector, not just parity tests.  This
smoke builds the tsan variant of the library (``make tsan`` →
``libbyteps_tpu_tsan.so``, a separate artifact so the production .so
never carries the 5-15x slowdown), then drives the striped fused +
resync hot paths from two concurrent workers in a subprocess running
under a preloaded libtsan, and fails on any ``WARNING:
ThreadSanitizer`` report.

Skips cleanly when the machine has no C++ compiler, no libtsan
runtime, or a runtime that cannot be preloaded into the Python
interpreter (some hardened distros).  Slow-marked: tier-1 never pays
the tsan build.

Lives OUTSIDE the ``*native*`` nodeid namespace on purpose: the
conftest native-hang guards (60s SIGALRM + faulthandler kill) assume
in-process ctypes calls, while everything here runs in bounded
subprocesses with their own timeouts.
"""

import os
import shutil
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE_DIR = os.path.join(_REPO, "byteps_tpu", "native")
_TSAN_SO = os.path.join(_NATIVE_DIR, "libbyteps_tpu_tsan.so")

#: two workers, striped across 4 reducers, hammering the paths the
#: striping rework touched: plain push/pull rounds on 8 keys (ring
#: handoff + shard locks + publish flush), a fused scatter/gather
#: (refcounted frame views + the FusedReply countdown), and a resync
#: snapshot racing the reducers (cross-stripe gather under shard locks).
_DRIVER = r"""
import ctypes, socket, struct, sys, threading

import numpy as np

from byteps_tpu.comm.transport import (
    Message, Op, encode_fused_push, encode_resync_query, recv_message,
    send_message,
)
from byteps_tpu.common.types import DataType, RequestType, get_command_type

lib = ctypes.CDLL(sys.argv[1])
lib.bps_native_server_start.argtypes = [ctypes.c_int32] * 3
lib.bps_native_server_start.restype = ctypes.c_int32
lib.bps_native_server_stop.argtypes = [ctypes.c_int32]
lib.bps_native_server_stop.restype = None

port = lib.bps_native_server_start(0, 2, 0)
assert port > 0, "tsan server start failed"

KEYS = list(range(8))
N = 32
CMD = get_command_type(RequestType.DEFAULT_PUSH_PULL, int(DataType.FLOAT32))
ROUNDS = 6
errors = []


def worker(flag):
    try:
        sock = socket.create_connection(("127.0.0.1", port), timeout=60)
        sock.settimeout(60)
        x = np.full(N, float(flag), dtype=np.float32)
        for k in KEYS:
            send_message(sock, Message(
                Op.INIT, key=k, seq=k, flags=flag,
                payload=struct.pack("!QI", N, int(DataType.FLOAT32)),
            ))
        for _ in KEYS:  # barrier of 2: acks return once both inited
            assert recv_message(sock).op == Op.INIT
        for rnd in range(1, ROUNDS + 1):
            for k in KEYS:
                send_message(sock, Message(
                    Op.PUSH, key=k, seq=100 * rnd + k, flags=flag, cmd=CMD,
                    version=rnd, payload=x.tobytes(),
                ))
            for _ in KEYS:
                assert recv_message(sock).op == Op.PUSH
            for k in KEYS:
                send_message(sock, Message(
                    Op.PULL, key=k, seq=200 * rnd + k, cmd=CMD, version=rnd,
                ))
            for _ in KEYS:
                assert recv_message(sock).op == Op.PULL
        # one fused frame per worker closes round ROUNDS+1 across every
        # key: members scatter to all 4 stripes, the countdown gathers
        members = [(k, CMD, ROUNDS + 1, x.tobytes()) for k in KEYS]
        send_message(sock, Message(
            Op.FUSED, key=KEYS[0], seq=999, flags=flag,
            payload=encode_fused_push(members),
        ))
        assert recv_message(sock).op == Op.FUSED
        # resync snapshot races the other worker's traffic
        send_message(sock, Message(
            Op.RESYNC_QUERY, key=0, seq=1000,
            payload=encode_resync_query(flag, KEYS),
        ))
        assert recv_message(sock).op == Op.RESYNC_STATE
        sock.close()
    except Exception as e:  # noqa: BLE001 — surfaced by the main thread
        errors.append(f"worker {flag}: {e!r}")


threads = [threading.Thread(target=worker, args=(f,)) for f in (1, 2)]
for t in threads:
    t.start()
for t in threads:
    t.join(timeout=120)
lib.bps_native_server_stop(port)
assert not errors, errors
print("TSAN-SMOKE-OK")
"""


def _libtsan_path():
    cxx = os.environ.get("CXX", "g++").split()[0]
    if shutil.which(cxx) is None:
        return None
    try:
        out = subprocess.run(
            [cxx, "-print-file-name=libtsan.so"],
            capture_output=True, text=True, timeout=30, check=True,
        ).stdout.strip()
    except (subprocess.SubprocessError, OSError):
        return None
    # an unresolved name comes back verbatim (not absolute) when the
    # runtime is not installed
    if not os.path.isabs(out) or not os.path.exists(out):
        return None
    return os.path.realpath(out)


@pytest.mark.slow
@pytest.mark.parametrize("stripes", ["4", "1"], ids=["striped", "inline"])
def test_tsan_striped_fused_resync_smoke(tmp_path, stripes):
    """stripes=4 races the ring handoff + 4 reducers; stripes=1 races
    the inline fast path (both serve threads summing under the one
    shard lock, no reducer thread)."""
    libtsan = _libtsan_path()
    if libtsan is None:
        pytest.skip("no C++ compiler or no libtsan runtime on this machine")
    build = subprocess.run(
        ["make", "-C", _NATIVE_DIR, "-s", "tsan"],
        capture_output=True, text=True, timeout=600,
    )
    if build.returncode != 0 or not os.path.exists(_TSAN_SO):
        pytest.skip(f"tsan build unavailable: {build.stderr[-500:]}")
    driver = tmp_path / "tsan_driver.py"
    driver.write_text(_DRIVER)
    env = dict(
        os.environ,
        PYTHONPATH=_REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        LD_PRELOAD=libtsan,
        BYTEPS_SERVER_STRIPES=stripes,
        # report everything, exit nonzero on races, don't flag the
        # interpreter's own (uninstrumented) thread shutdown order; the
        # suppressions file silences ONLY the pthread_cond_clockwait
        # mutex-report false positive (see native/tsan.supp) — data-race
        # reports stay fatal
        TSAN_OPTIONS=(
            "halt_on_error=0 exit_code=66 report_thread_leaks=0 "
            f"suppressions={os.path.join(_NATIVE_DIR, 'tsan.supp')}"
        ),
    )
    proc = subprocess.run(
        [sys.executable, str(driver), _TSAN_SO],
        capture_output=True, text=True, timeout=480, cwd=_REPO, env=env,
    )
    out = proc.stdout + "\n" + proc.stderr
    if "WARNING: ThreadSanitizer" in out:
        pytest.fail(
            "ThreadSanitizer reported race(s) in the striped engine:\n"
            + out[-8000:]
        )
    if "TSAN-SMOKE-OK" not in out:
        # the runtime refused to bootstrap under LD_PRELOAD (hardened
        # allocators, container seccomp): an environment limit, not an
        # engine race — skip, don't fail
        if "ThreadSanitizer" in out or "LD_PRELOAD" in out or proc.returncode != 0:
            pytest.skip(
                f"tsan runtime unusable here (rc={proc.returncode}): "
                + out[-500:]
            )
    assert proc.returncode == 0, out[-3000:]
