"""Golden wire-frame fixtures: byte-exact encoded vectors for
PUSH/PULL/INIT/FUSED/RESYNC — with and without the 16-byte trace block —
asserted against BOTH the Python framing (comm/transport.py) and the C++
codec (native/wire.h pack_header + the ps_server.cc fused/resync
encoders/decoders, via the bps_wire_* shims), so the two implementations
can never drift silently.

Three anchors per fixture set:

- transport.py builds the frames;
- the C++ shim builds the same frames through the LIVE engine code paths
  (pack_header is the one header encoder ps_server.cc send_msg and
  ps_client.cc bpsc_send go through);
- a frozen hex digest pins both to the wire format as SHIPPED — a
  same-bug-on-both-sides refactor still fails the test.
"""

import ctypes
import hashlib
import struct

import pytest

from byteps_tpu.comm.transport import (
    Message,
    Op,
    decode_fused_push,
    decode_resync_query,
    encode_fused_push,
    encode_fused_reply,
    encode_resync_query,
    encode_resync_state,
)


def _lib():
    from byteps_tpu.native import get_lib

    lib = get_lib()
    if lib is None or not hasattr(lib, "bps_wire_golden"):
        return None
    return lib


pytestmark = pytest.mark.skipif(
    _lib() is None, reason="native lib (with wire shims) not built"
)

#: sha256 of the fixture byte stream as frozen at the native-parity port —
#: pins BOTH codecs to the shipped wire format, not merely to each other
GOLDEN_SHA256 = "29ef1635893fd36ae7520635c170429cca14e201d34710f955ed0fb6950de145"


def python_golden_frames() -> bytes:
    """The fixture stream, built by transport.py.  Mirrors the fixture
    list in ps_server.cc bps_wire_golden — change both together (the
    frozen digest will catch a one-sided edit)."""
    out = b""
    # A: plain PUSH, payload bytes 0..7
    out += Message(Op.PUSH, key=42, payload=bytes(range(8)), seq=7, cmd=6,
                   version=3, flags=1).encode()
    # B: the same PUSH carrying trace context (status bit 7 + 16 bytes)
    out += Message(Op.PUSH, key=42, payload=bytes(range(8)), seq=7, cmd=6,
                   version=3, flags=1,
                   trace=(0x1122334455667788, 0x99AABBCCDDEEFF00)).encode()
    # C: PULL request (empty payload)
    out += Message(Op.PULL, key=42, seq=8, cmd=6, version=3).encode()
    # D: INIT carrying an idempotency token in ``version``
    out += Message(Op.INIT, key=43, seq=9, flags=2, version=0xA0001,
                   payload=struct.pack("!QI", 32, 0)).encode()
    # E: a FUSED multi-key reply (one empty member payload)
    fused = encode_fused_reply([(101, 1, b"wxyz"), (202, 2, b"")])
    out += Message(Op.FUSED, key=101, seq=10, payload=fused).encode()
    # F: a RESYNC_STATE ledger snapshot (two keys)
    state = encode_resync_state({
        5: {"store_version": 4, "seen": 3, "recv_count": 1, "init": True},
        9: {"store_version": 0, "seen": 0, "recv_count": 0, "init": True},
    })
    out += Message(Op.RESYNC_STATE, key=5, seq=11, payload=state).encode()
    return out


def native_golden_frames() -> bytes:
    lib = _lib()
    buf = (ctypes.c_uint8 * 8192)()
    n = lib.bps_wire_golden(buf, len(buf))
    assert n > 0, f"bps_wire_golden failed: {n}"
    return bytes(buf[:n])


class TestGoldenFrames:
    def test_native_codec_matches_python(self):
        py = python_golden_frames()
        cc = native_golden_frames()
        assert py == cc, (
            "C++ and Python wire encodings diverged "
            f"(first diff at byte {next(i for i, (a, b) in enumerate(zip(py, cc)) if a != b) if py[:min(len(py), len(cc))] != cc[:min(len(py), len(cc))] else min(len(py), len(cc))})"
        )

    def test_frames_match_frozen_digest(self):
        digest = hashlib.sha256(python_golden_frames()).hexdigest()
        assert digest == GOLDEN_SHA256, (
            "the wire format changed — if that is intentional, this is a "
            "PROTOCOL revision: update GOLDEN_SHA256 and audit every "
            "decoder (Python AND C++) for compatibility"
        )


def _fused_echo(body: bytes) -> bytes:
    lib = _lib()
    out = (ctypes.c_uint8 * (len(body) + 64))()
    n = lib.bps_wire_fused_echo(body, len(body), out, len(out))
    assert n >= 0, f"native fused decode failed: {n}"
    return bytes(out[:n])


class TestFusedDecodeParity:
    MEMBERS = [
        (101, 6, 1, b"abcd"),
        (1 << 40, 0, 9, b""),
        (202, 11, 2, bytes(range(64))),
    ]

    def test_native_decodes_python_frames(self):
        body = encode_fused_push(self.MEMBERS)
        assert _fused_echo(body) == body
        assert decode_fused_push(body) == self.MEMBERS

    def test_native_ignores_span_trailer(self):
        """The optional member-span trailer (tracing) must be invisible
        to the decoder — old-decoder compatibility, transport.py
        contract."""
        with_trailer = encode_fused_push(self.MEMBERS, span_ids=[7, 8, 9])
        without = encode_fused_push(self.MEMBERS)
        assert _fused_echo(with_trailer) == without

    def test_native_rejects_truncated_frame(self):
        lib = _lib()
        body = encode_fused_push(self.MEMBERS)[:-3]
        out = (ctypes.c_uint8 * 1024)()
        assert lib.bps_wire_fused_echo(body, len(body), out, 1024) == -1

    def test_native_rejects_empty_frame(self):
        lib = _lib()
        body = struct.pack("!I", 0)
        out = (ctypes.c_uint8 * 16)()
        assert lib.bps_wire_fused_echo(body, len(body), out, 16) == -1


def _resync_echo(body: bytes):
    lib = _lib()
    out = (ctypes.c_uint8 * 4096)()
    n = lib.bps_wire_resync_echo(body, len(body), out, len(out))
    if n < 0:
        return None
    return bytes(out[:n]).decode()


class TestResyncDecodeParity:
    def test_native_parses_python_query(self):
        body = encode_resync_query(3, [7, 9, 1 << 40])
        assert _resync_echo(body) == f"3|7,9,{1 << 40}"
        assert decode_resync_query(body) == (3, [7, 9, 1 << 40])

    def test_native_parses_empty_keys_as_all(self):
        assert _resync_echo(encode_resync_query(1, [])) == "1|"

    def test_native_rejects_non_object_body(self):
        # same malformed body the Python decoder raises ValueError on
        with pytest.raises(ValueError):
            decode_resync_query(b"[1, 2, 3]")
        assert _resync_echo(b"[1, 2, 3]") is None
