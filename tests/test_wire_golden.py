"""Golden wire-frame fixtures: byte-exact encoded vectors for
PUSH/PULL/INIT/FUSED/RESYNC — with and without the 16-byte trace block —
asserted against BOTH the Python framing (comm/transport.py) and the C++
codec (native/wire.h pack_header + the ps_server.cc fused/resync
encoders/decoders, via the bps_wire_* shims), so the two implementations
can never drift silently.

Three anchors per fixture set:

- transport.py builds the frames;
- the C++ shim builds the same frames through the LIVE engine code paths
  (pack_header is the one header encoder ps_server.cc send_msg and
  ps_client.cc bpsc_send go through);
- a frozen hex digest pins both to the wire format as SHIPPED — a
  same-bug-on-both-sides refactor still fails the test.
"""

import ctypes
import hashlib
import struct

import pytest

from byteps_tpu.comm.transport import (
    Message,
    Op,
    decode_fused_push,
    decode_resync_query,
    encode_fused_push,
    encode_fused_reply,
    encode_resync_query,
    encode_resync_state,
)


def _lib():
    from byteps_tpu.native import get_lib

    lib = get_lib()
    if lib is None or not hasattr(lib, "bps_wire_golden"):
        return None
    return lib


pytestmark = pytest.mark.skipif(
    _lib() is None, reason="native lib (with wire shims) not built"
)

#: sha256 of the fixture byte stream as frozen at the native-parity port —
#: pins BOTH codecs to the shipped wire format, not merely to each other
GOLDEN_SHA256 = "29ef1635893fd36ae7520635c170429cca14e201d34710f955ed0fb6950de145"


def python_golden_frames() -> bytes:
    """The fixture stream, built by transport.py.  Mirrors the fixture
    list in ps_server.cc bps_wire_golden — change both together (the
    frozen digest will catch a one-sided edit)."""
    out = b""
    # A: plain PUSH, payload bytes 0..7
    out += Message(Op.PUSH, key=42, payload=bytes(range(8)), seq=7, cmd=6,
                   version=3, flags=1).encode()
    # B: the same PUSH carrying trace context (status bit 7 + 16 bytes)
    out += Message(Op.PUSH, key=42, payload=bytes(range(8)), seq=7, cmd=6,
                   version=3, flags=1,
                   trace=(0x1122334455667788, 0x99AABBCCDDEEFF00)).encode()
    # C: PULL request (empty payload)
    out += Message(Op.PULL, key=42, seq=8, cmd=6, version=3).encode()
    # D: INIT carrying an idempotency token in ``version``
    out += Message(Op.INIT, key=43, seq=9, flags=2, version=0xA0001,
                   payload=struct.pack("!QI", 32, 0)).encode()
    # E: a FUSED multi-key reply (one empty member payload)
    fused = encode_fused_reply([(101, 1, b"wxyz"), (202, 2, b"")])
    out += Message(Op.FUSED, key=101, seq=10, payload=fused).encode()
    # F: a RESYNC_STATE ledger snapshot (two keys)
    state = encode_resync_state({
        5: {"store_version": 4, "seen": 3, "recv_count": 1, "init": True},
        9: {"store_version": 0, "seen": 0, "recv_count": 0, "init": True},
    })
    out += Message(Op.RESYNC_STATE, key=5, seq=11, payload=state).encode()
    return out


def native_golden_frames() -> bytes:
    lib = _lib()
    buf = (ctypes.c_uint8 * 8192)()
    n = lib.bps_wire_golden(buf, len(buf))
    assert n > 0, f"bps_wire_golden failed: {n}"
    return bytes(buf[:n])


class TestGoldenFrames:
    def test_native_codec_matches_python(self):
        py = python_golden_frames()
        cc = native_golden_frames()
        assert py == cc, (
            "C++ and Python wire encodings diverged "
            f"(first diff at byte {next(i for i, (a, b) in enumerate(zip(py, cc)) if a != b) if py[:min(len(py), len(cc))] != cc[:min(len(py), len(cc))] else min(len(py), len(cc))})"
        )

    def test_frames_match_frozen_digest(self):
        digest = hashlib.sha256(python_golden_frames()).hexdigest()
        assert digest == GOLDEN_SHA256, (
            "the wire format changed — if that is intentional, this is a "
            "PROTOCOL revision: update GOLDEN_SHA256 and audit every "
            "decoder (Python AND C++) for compatibility"
        )


#: sha256 of the compressed-wire-path fixture stream (compressed fused
#: push/reply + codec registration) as frozen at the compressed-fused
#: port — a SEPARATE stream so the original GOLDEN_SHA256 frames stay
#: byte-identical (these EXTEND the fixture set, no protocol revision)
COMPRESSED_GOLDEN_SHA256 = (
    "710311daf22719e13ef04dbf30e2bbcff75436db94d952fd13fd131ffd22b8f3"
)


def python_compressed_golden_frames() -> bytes:
    """Compressed-wire-path fixtures via transport.py: a fused PUSH whose
    members carry the per-member compressed flag — RequestType
    .COMPRESSED_PUSH_PULL Cantor-encoded in the member cmd — beside a
    raw sibling, with the member-span trailer and outer trace context;
    the codec-compressed fused REPLY; and the REGISTER_COMPRESSOR frame
    that arms the server-side chain.  Mirrors ps_server.cc
    bps_wire_golden_compressed — change both together."""
    from byteps_tpu.common.types import DataType, RequestType, get_command_type

    cmd_comp = get_command_type(RequestType.COMPRESSED_PUSH_PULL,
                                int(DataType.FLOAT32))
    cmd_raw = get_command_type(RequestType.DEFAULT_PUSH_PULL,
                               int(DataType.FLOAT32))
    # onebit-shaped payload: f32 scale 0.5 + two u32 sign words, LE
    # (compressor.cc wire format)
    onebit = (struct.pack("<f", 0.5)
              + struct.pack("<II", 0xDEADBEEF, 0x01234567))
    raw = bytes(range(1, 9))
    out = b""
    # G: compressed fused PUSH (trailer + trace context)
    body = encode_fused_push(
        [(301, cmd_comp, 5, onebit), (302, cmd_raw, 5, raw)],
        span_ids=[0xC0FFEE0000000001, 0xC0FFEE0000000002],
    )
    out += Message(Op.FUSED, key=301, payload=body, seq=31, cmd=2, flags=1,
                   trace=(0x5555555555555555, 0x6666666666666666)).encode()
    # H: the fused REPLY with a codec-compressed slot beside a raw one
    reply = encode_fused_reply([(301, 5, onebit), (302, 5, raw)])
    out += Message(Op.FUSED, key=301, payload=reply, seq=31).encode()
    # I: codec-config registration (newline key=value text)
    reg = b"byteps_compressor_type=onebit\nbyteps_ef_type=vanilla"
    out += Message(Op.REGISTER_COMPRESSOR, key=301, payload=reg,
                   seq=32).encode()
    return out


class TestCompressedGoldenFrames:
    def test_native_codec_matches_python(self):
        lib = _lib()
        if not hasattr(lib, "bps_wire_golden_compressed"):
            pytest.skip("lib predates the compressed-wire-path shim")
        buf = (ctypes.c_uint8 * 8192)()
        n = lib.bps_wire_golden_compressed(buf, len(buf))
        assert n > 0, f"bps_wire_golden_compressed failed: {n}"
        assert bytes(buf[:n]) == python_compressed_golden_frames()

    def test_frames_match_frozen_digest(self):
        digest = hashlib.sha256(
            python_compressed_golden_frames()
        ).hexdigest()
        assert digest == COMPRESSED_GOLDEN_SHA256, (
            "the compressed-fused wire format changed — a PROTOCOL "
            "revision: update COMPRESSED_GOLDEN_SHA256 and audit every "
            "decoder (Python AND C++) for compatibility"
        )

    def test_old_decoder_compat_on_compressed_frame(self):
        """The compressed-flag member cmd and the span trailer must both
        be invisible to a pre-compression fused decoder: decode yields
        exactly the members (trailer ignored), and the member cmd is an
        opaque u32 it already carried."""
        from byteps_tpu.common.types import (
            DataType, RequestType, decode_command_type, get_command_type,
        )

        cmd_comp = get_command_type(RequestType.COMPRESSED_PUSH_PULL,
                                    int(DataType.FLOAT32))
        members = [(301, cmd_comp, 5, b"\x01\x02"), (302, 0, 5, b"\x03")]
        body = encode_fused_push(members, span_ids=[7, 8])
        assert decode_fused_push(body) == members
        rtype, dtype = decode_command_type(cmd_comp)
        assert rtype == RequestType.COMPRESSED_PUSH_PULL
        assert dtype == int(DataType.FLOAT32)
        # the native decoder sees the same two members, trailer dropped
        assert _fused_echo(body) == encode_fused_push(members)


#: sha256 of the CHECKSUMMED fixture stream (CHECKSUM_FLAG + 4-byte
#: CRC32C after the header/trace block; docs/robustness.md "Wire
#: integrity") as frozen at the wire-integrity port — a SEPARATE stream,
#: so every pre-checksum digest above stays byte-identical (default-off
#: compat: flag off ⇒ the existing GOLDEN streams are unchanged)
CHECKSUM_GOLDEN_SHA256 = (
    "bd1891fb581e892c85501f5a201c1d808b647cd98465fc8d3df0c40f9846089f"
)


def python_checksum_golden_frames() -> bytes:
    """The checksummed fixture stream via transport.py: the SAME wire
    shapes as the plain/compressed streams — PUSH ± trace, PULL, the
    compressed fused PUSH with trailer + trace, the codec-compressed
    fused REPLY — with ``checksum=True`` forcing the CHECKSUM_FLAG
    stamp.  Mirrors ps_server.cc bps_wire_golden_checksum — change both
    together."""
    from byteps_tpu.common.types import DataType, RequestType, get_command_type

    cmd_comp = get_command_type(RequestType.COMPRESSED_PUSH_PULL,
                                int(DataType.FLOAT32))
    cmd_raw = get_command_type(RequestType.DEFAULT_PUSH_PULL,
                               int(DataType.FLOAT32))
    onebit = (struct.pack("<f", 0.5)
              + struct.pack("<II", 0xDEADBEEF, 0x01234567))
    raw = bytes(range(1, 9))
    out = b""
    # J: checksummed plain PUSH
    out += Message(Op.PUSH, key=42, payload=bytes(range(8)), seq=7, cmd=6,
                   version=3, flags=1, checksum=True).encode()
    # K: the same PUSH with trace context — CRC covers trace + payload
    out += Message(Op.PUSH, key=42, payload=bytes(range(8)), seq=7, cmd=6,
                   version=3, flags=1,
                   trace=(0x1122334455667788, 0x99AABBCCDDEEFF00),
                   checksum=True).encode()
    # L: checksummed PULL (empty payload)
    out += Message(Op.PULL, key=42, seq=8, cmd=6, version=3,
                   checksum=True).encode()
    # M: checksummed compressed fused PUSH (trailer + trace context)
    body = encode_fused_push(
        [(301, cmd_comp, 5, onebit), (302, cmd_raw, 5, raw)],
        span_ids=[0xC0FFEE0000000001, 0xC0FFEE0000000002],
    )
    out += Message(Op.FUSED, key=301, payload=body, seq=31, cmd=2, flags=1,
                   trace=(0x5555555555555555, 0x6666666666666666),
                   checksum=True).encode()
    # N: the checksummed codec-compressed fused REPLY
    reply = encode_fused_reply([(301, 5, onebit), (302, 5, raw)])
    out += Message(Op.FUSED, key=301, payload=reply, seq=31,
                   checksum=True).encode()
    return out


class TestChecksumGoldenFrames:
    def test_native_codec_matches_python(self):
        lib = _lib()
        if not hasattr(lib, "bps_wire_golden_checksum"):
            pytest.skip("lib predates the wire-integrity shim")
        buf = (ctypes.c_uint8 * 8192)()
        n = lib.bps_wire_golden_checksum(buf, len(buf))
        assert n > 0, f"bps_wire_golden_checksum failed: {n}"
        assert bytes(buf[:n]) == python_checksum_golden_frames()

    def test_frames_match_frozen_digest(self):
        digest = hashlib.sha256(python_checksum_golden_frames()).hexdigest()
        assert digest == CHECKSUM_GOLDEN_SHA256, (
            "the checksummed wire format changed — a PROTOCOL revision: "
            "update CHECKSUM_GOLDEN_SHA256 and audit every decoder "
            "(Python AND C++) for compatibility"
        )

    def test_client_encoder_checksummed_frames_match(self):
        """The native CLIENT's checksummed encode path
        (bps_wire_client_frame_ck — the bytes bpsc_send2 writes under
        BYTEPS_WIRE_CHECKSUM=1) against transport.py, frame by frame."""
        lib = _lib()
        if not hasattr(lib, "bps_wire_client_frame_ck"):
            pytest.skip("lib predates the wire-integrity shim")
        cases = [
            (Op.PUSH, 21, 42, 6, 3, 1, None, bytes(range(8))),
            (Op.PUSH, 21, 42, 6, 3, 1,
             (0x0123456789ABCDEF, 0x0FEDCBA987654321), bytes(range(8))),
            (Op.PULL, 22, 42, 6, 3, 0, None, b""),
            (Op.FUSED, 24, 101, 2, 0, 1,
             (0x3333333333333333, 0x4444444444444444),
             encode_fused_push([(101, 6, 1, b"abcd")], span_ids=[0xA1])),
        ]
        for op, seq, key, cmd, ver, flags, trace, payload in cases:
            out = (ctypes.c_uint8 * (len(payload) + 64))()
            t, s = trace if trace else (0, 0)
            n = lib.bps_wire_client_frame_ck(
                int(op), seq, key, cmd, ver, flags, t, s, bytes(payload),
                len(payload), out, len(out),
            )
            assert n > 0
            py = Message(op, key=key, payload=payload, seq=seq, cmd=cmd,
                         version=ver, flags=flags, trace=trace,
                         checksum=True).encode()
            assert bytes(out[:n]) == py

    def test_checksum_off_keeps_existing_streams_byte_identical(self):
        """Old-decoder compat: with the flag off, every pre-checksum
        fixture stream is untouched (their frozen digests above are the
        stronger pin; this asserts the checksum attribute's default
        never leaks into unstamped encodes even under the env knob)."""
        import os

        assert "BYTEPS_WIRE_CHECKSUM" not in os.environ or \
            os.environ["BYTEPS_WIRE_CHECKSUM"] in ("", "0")
        assert hashlib.sha256(
            python_golden_frames()
        ).hexdigest() == GOLDEN_SHA256
        # an explicit checksum=False wins over the env knob
        os.environ["BYTEPS_WIRE_CHECKSUM"] = "1"
        try:
            framed = Message(Op.PUSH, key=1, payload=b"xy", seq=1,
                             checksum=False).encode()
        finally:
            os.environ.pop("BYTEPS_WIRE_CHECKSUM")
        assert framed == Message(Op.PUSH, key=1, payload=b"xy", seq=1,
                                 checksum=False).encode()
        assert len(framed) == 32 + 2  # no checksum block


#: sha256 of the CLIENT-encoder fixture stream (trace-flagged frames
#: through bps_wire_client_frame, the live bpsc_send2 path) as frozen at
#: the native-observability port
CLIENT_GOLDEN_SHA256 = (
    "f9f374ed7bfd26fe3aba64732883f46eccaea3661d0924852ee4414d639bd557"
)


def _client_frame(op, seq, key, cmd, version, flags, trace, payload) -> bytes:
    """One frame through the LIVE native client encoder (the same
    build_frame_head bytes bpsc_send2 writes)."""
    lib = _lib()
    out = (ctypes.c_uint8 * (len(payload) + 64))()
    t, s = trace if trace else (0, 0)
    n = lib.bps_wire_client_frame(
        int(op), seq, key, cmd, version, flags, t, s, bytes(payload),
        len(payload), out, len(out),
    )
    assert n > 0, f"bps_wire_client_frame failed: {n}"
    return bytes(out[:n])


def client_golden_frames() -> bytes:
    """Trace-context fixtures through the native CLIENT encoder — the
    direction the Python fixtures above don't pin (bps_wire_golden goes
    through the server-side pack_header path; bpsc_send2's framing —
    TRACE_FLAG status bit + 16-byte block placement — is what these
    freeze).  Mirrors the transport.py frames 1:1."""
    frames = [
        # traced PUSH (the hot-path case: engine span context on a push)
        (Op.PUSH, 21, 42, 6, 3, 1, (0x0123456789ABCDEF, 0x0FEDCBA987654321),
         bytes(range(8))),
        # traced PULL (empty payload + trace block)
        (Op.PULL, 22, 42, 6, 3, 0, (0x1111111111111111, 0x2222222222222222),
         b""),
        # UNtraced PUSH through the same encoder (no block, status clean)
        (Op.PUSH, 23, 42, 6, 4, 1, None, bytes(range(8))),
        # traced FUSED frame whose body carries the member-span TRAILER
        # (encode_fused_push span_ids) — trailer bytes ride as payload,
        # outer header carries the pack's trace context
        (Op.FUSED, 24, 101, 2, 0, 1, (0x3333333333333333, 0x4444444444444444),
         encode_fused_push(
             [(101, 6, 1, b"abcd"), (202, 11, 2, b"wxyz")],
             span_ids=[0xAAAAAAAAAAAAAAA1, 0xBBBBBBBBBBBBBBB2],
         )),
    ]
    return b"".join(_client_frame(*f) for f in frames)


def python_client_golden_frames() -> bytes:
    """The same frames via transport.py Message.encode."""
    out = b""
    out += Message(Op.PUSH, key=42, payload=bytes(range(8)), seq=21, cmd=6,
                   version=3, flags=1,
                   trace=(0x0123456789ABCDEF, 0x0FEDCBA987654321)).encode()
    out += Message(Op.PULL, key=42, seq=22, cmd=6, version=3,
                   trace=(0x1111111111111111, 0x2222222222222222)).encode()
    out += Message(Op.PUSH, key=42, payload=bytes(range(8)), seq=23, cmd=6,
                   version=4, flags=1).encode()
    fused = encode_fused_push(
        [(101, 6, 1, b"abcd"), (202, 11, 2, b"wxyz")],
        span_ids=[0xAAAAAAAAAAAAAAA1, 0xBBBBBBBBBBBBBBB2],
    )
    out += Message(Op.FUSED, key=101, payload=fused, seq=24, cmd=2, flags=1,
                   trace=(0x3333333333333333, 0x4444444444444444)).encode()
    return out


class TestClientGoldenFrames:
    def test_client_encoder_matches_python(self):
        if not hasattr(_lib(), "bps_wire_client_frame"):
            pytest.skip("lib predates the client golden shim")
        assert client_golden_frames() == python_client_golden_frames()

    def test_client_frames_match_frozen_digest(self):
        digest = hashlib.sha256(python_client_golden_frames()).hexdigest()
        assert digest == CLIENT_GOLDEN_SHA256, (
            "the trace-context wire format changed — a PROTOCOL revision: "
            "update CLIENT_GOLDEN_SHA256 and audit every decoder"
        )

    def test_native_trailer_parser_recovers_span_ids(self):
        """The fused member-span TRAILER through the live native parser
        (the ids handle_fused parents member child spans onto) must
        round-trip the Python encoder's ids exactly."""
        lib = _lib()
        if not hasattr(lib, "bps_wire_fused_spans_echo"):
            pytest.skip("lib predates the trailer-parser shim")
        members = [(101, 6, 1, b"abcd"), (1 << 40, 0, 9, b"")]
        ids = [0x1234, (1 << 63) | 1]
        body = encode_fused_push(members, span_ids=ids)
        out = (ctypes.c_uint64 * 8)()
        n = lib.bps_wire_fused_spans_echo(body, len(body), out, 8)
        assert n == 2 and list(out[:2]) == ids
        # trailer-less body: parser reports none (old-sender compat)
        plain = encode_fused_push(members)
        assert lib.bps_wire_fused_spans_echo(plain, len(plain), out, 8) == 0


def _fused_echo(body: bytes) -> bytes:
    lib = _lib()
    out = (ctypes.c_uint8 * (len(body) + 64))()
    n = lib.bps_wire_fused_echo(body, len(body), out, len(out))
    assert n >= 0, f"native fused decode failed: {n}"
    return bytes(out[:n])


class TestFusedDecodeParity:
    MEMBERS = [
        (101, 6, 1, b"abcd"),
        (1 << 40, 0, 9, b""),
        (202, 11, 2, bytes(range(64))),
    ]

    def test_native_decodes_python_frames(self):
        body = encode_fused_push(self.MEMBERS)
        assert _fused_echo(body) == body
        assert decode_fused_push(body) == self.MEMBERS

    def test_native_ignores_span_trailer(self):
        """The optional member-span trailer (tracing) must be invisible
        to the decoder — old-decoder compatibility, transport.py
        contract."""
        with_trailer = encode_fused_push(self.MEMBERS, span_ids=[7, 8, 9])
        without = encode_fused_push(self.MEMBERS)
        assert _fused_echo(with_trailer) == without

    def test_native_rejects_truncated_frame(self):
        lib = _lib()
        body = encode_fused_push(self.MEMBERS)[:-3]
        out = (ctypes.c_uint8 * 1024)()
        assert lib.bps_wire_fused_echo(body, len(body), out, 1024) == -1

    def test_native_rejects_empty_frame(self):
        lib = _lib()
        body = struct.pack("!I", 0)
        out = (ctypes.c_uint8 * 16)()
        assert lib.bps_wire_fused_echo(body, len(body), out, 16) == -1


def _resync_echo(body: bytes):
    lib = _lib()
    out = (ctypes.c_uint8 * 4096)()
    n = lib.bps_wire_resync_echo(body, len(body), out, len(out))
    if n < 0:
        return None
    return bytes(out[:n]).decode()


class TestResyncDecodeParity:
    def test_native_parses_python_query(self):
        body = encode_resync_query(3, [7, 9, 1 << 40])
        assert _resync_echo(body) == f"3|7,9,{1 << 40}"
        assert decode_resync_query(body) == (3, [7, 9, 1 << 40])

    def test_native_parses_empty_keys_as_all(self):
        assert _resync_echo(encode_resync_query(1, [])) == "1|"

    def test_native_rejects_non_object_body(self):
        # same malformed body the Python decoder raises ValueError on
        with pytest.raises(ValueError):
            decode_resync_query(b"[1, 2, 3]")
        assert _resync_echo(b"[1, 2, 3]") is None


class TestKeyStripeGolden:
    """The key→reducer-stripe mapping (wire.h ``key_stripe``) is wire-
    adjacent state: tests and operators reason about which keys share a
    reducer, so the mapping is pinned like a codec — a hash tweak must
    be a deliberate, test-visible change."""

    #: frozen against the shipped splitmix64 finalizer (change together
    #: with wire.h key_stripe)
    FROZEN_4 = {0: 3, 1: 1, 2: 2, 3: 1, 4: 2, 5: 2, 6: 0, 7: 3,
                8: 2, 9: 0, 10: 2, 11: 1, 12: 3, 13: 3, 14: 2, 15: 1}

    def test_live_mapping_matches_frozen(self):
        from byteps_tpu.native import HAVE_NATIVE, key_stripe

        if not HAVE_NATIVE:
            # key_stripe's pure-Python stand-in is key % n — explicitly
            # NOT the shipped hash this pin is about
            pytest.skip("native lib not built")
        assert {k: key_stripe(k, 4) for k in self.FROZEN_4} == self.FROZEN_4

    def test_one_stripe_is_identity_zero(self):
        from byteps_tpu.native import key_stripe

        assert all(key_stripe(k, 1) == 0 for k in range(64))

    def test_mapping_spreads_small_dense_keys(self):
        # tensor keys are small dense ints (partition ids): the finalizer
        # must not alias them onto few stripes
        from byteps_tpu.native import HAVE_NATIVE, key_stripe

        if not HAVE_NATIVE:
            pytest.skip("native lib not built")  # % n fallback ≠ the hash
        used = {key_stripe(k, 4) for k in range(64)}
        assert used == {0, 1, 2, 3}


class TestStripedServerGolden:
    """Bitwise pin for the key-striped engine: ONE scripted lockstep
    exchange (init barrier, three push/pull rounds, a fused frame, a
    resync snapshot) against a 1-stripe and a 4-stripe native server
    must produce identical reply bytes — striping may change WHERE a sum
    runs, never what goes on the wire."""

    def _digest(self, stripes: int, monkeypatch) -> str:
        import numpy as np

        from byteps_tpu.common.config import Config
        from byteps_tpu.common.types import (
            DataType, RequestType, get_command_type,
        )
        from byteps_tpu.comm.transport import connect, recv_message, send_message
        from byteps_tpu.server.server import NativePSServer

        monkeypatch.setenv("BYTEPS_SERVER_STRIPES", str(stripes))
        cfg = Config(num_worker=1, num_server=1)
        srv = NativePSServer(cfg)
        h = hashlib.sha256()

        def absorb(msg):
            h.update(struct.pack(
                "!BIQIB", int(msg.op), msg.seq, msg.key, msg.version,
                msg.flags,
            ))
            h.update(msg.payload or b"")

        try:
            sock = connect(srv.host, srv.port)
            cmd = get_command_type(RequestType.DEFAULT_PUSH_PULL,
                                   int(DataType.FLOAT32))
            # spans all 4 stripes: FROZEN_4 (TestKeyStripeGolden) maps
            # keys 0-6 to stripes {3,1,2,1,2,2,0} — key 6 is the only
            # one of these on stripe 0, so range(7), not range(6)
            KEYS = list(range(7))
            N = 16
            for k in KEYS:
                send_message(sock, Message(
                    Op.INIT, key=k, seq=k, flags=1,
                    payload=struct.pack("!QI", N, int(DataType.FLOAT32)),
                ))
                absorb(recv_message(sock))
            for rnd in range(1, 4):
                for k in KEYS:
                    x = np.arange(N, dtype=np.float32) * rnd + k
                    send_message(sock, Message(
                        Op.PUSH, key=k, seq=100 * rnd + k, flags=1, cmd=cmd,
                        version=rnd, payload=x.tobytes(),
                    ))
                    absorb(recv_message(sock))
                for k in KEYS:
                    send_message(sock, Message(
                        Op.PULL, key=k, seq=200 * rnd + k, cmd=cmd,
                        version=rnd,
                    ))
                    absorb(recv_message(sock))
            frame = encode_fused_push([
                (k, cmd, 4, np.full(N, k + 0.5, dtype=np.float32).tobytes())
                for k in KEYS
            ])
            send_message(sock, Message(Op.FUSED, key=KEYS[0], seq=999,
                                       flags=1, payload=frame))
            absorb(recv_message(sock))
            send_message(sock, Message(
                Op.RESYNC_QUERY, key=0, seq=1000,
                payload=encode_resync_query(1, []),
            ))
            absorb(recv_message(sock))
            from byteps_tpu.comm.transport import close_socket

            close_socket(sock)
        finally:
            srv.stop()
        return h.hexdigest()

    def test_native_striped_replies_bitwise_identical(self, monkeypatch):
        from conftest import have_native_parity_server

        if not have_native_parity_server():
            pytest.skip("native lib (with parity surface) not built")
        assert self._digest(1, monkeypatch) == self._digest(4, monkeypatch)
