"""End-to-end wire integrity (docs/robustness.md "Wire integrity"):
payload checksums, corruption quarantine, and the detectable-corruption
chaos mode.

Layers under test:

- the shared CRC32C: known vectors, chaining, pure-Python ↔ native
  (wire.h) parity;
- the CHECKSUM_FLAG codec: stamp/strip round trips (± trace block),
  drop-semantics on mismatch (the stream stays framed — the NEXT frame
  decodes), non-verifying consumers stay framed, control ops never
  stamp, explicit overrides beat the env knob;
- the chaos van's payload-corrupt fault: seeded single-bit flip past
  the fixed header, composing with op targeting and the fault budget;
- tools/wire_fuzz.py smoke (the raise-or-checksum-reject contract);
- verify-and-heal, wire level, parametrized over
  {python, native-s1, native-s4} × {fused, unfused} × {raw, onebit}:
  a corrupted push is dropped without a reply and without touching the
  ledger, the clean resend sums once, a replay dedupes, pulls stay
  bitwise;
- connection quarantine: BYTEPS_CHECKSUM_CONN_LIMIT mismatches drop
  the connection on both server engines (and a fresh dial serves);
- client-side verification: corrupted replies (fused multi-key,
  RESYNC_STATE shapes) are dropped BEFORE the seq demux by the Python
  recv lanes and the native client's C++ lanes, the pending callback
  surviving for the retry; the conn-limit escalation poisons the
  connection so revival re-dials;
- end-to-end: a corrupted fused frame heals through deadline/retry with
  bitwise pulls; a permanently-corrupted RESYNC_STATE stream fails the
  heal CLEANLY to the re-init path (resync_giveup, key marked, no
  hang);
- observability: the corruption_storm flight trigger and the
  wire_corruption doctor rule fire on the right shapes.
"""

import importlib.util
import os
import socket
import struct
import sys
import threading
import time

import numpy as np
import pytest

from byteps_tpu.common.config import Config
from byteps_tpu.common.types import DataType, RequestType, get_command_type
from byteps_tpu.comm.chaos import ChaosParams, ChaosSocket, reset_fault_budget
from byteps_tpu.comm.transport import (
    CHECKSUM_FLAG,
    HEADER_SIZE,
    ChecksumError,
    Message,
    Op,
    close_socket,
    connect,
    crc32c,
    decode_fused_reply,
    encode_fused_push,
    encode_fused_reply,
    frame_checksum,
    recv_header,
    recv_message,
    send_message,
)
from byteps_tpu.core.telemetry import counters
from conftest import (
    ENGINE_STRIPES,
    ENGINE_STRIPES_IDS,
    make_ps_server,
    require_engine,
    set_stripes,
)

CMD_F32 = get_command_type(RequestType.DEFAULT_PUSH_PULL, int(DataType.FLOAT32))
CMD_COMP = get_command_type(RequestType.COMPRESSED_PUSH_PULL,
                            int(DataType.FLOAT32))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _flip(frame: bytes, offset: int, bit: int = 0) -> bytes:
    b = bytearray(frame)
    b[offset] ^= 1 << bit
    return bytes(b)


# --------------------------------------------------------------------------
# CRC32C


class TestCrc32c:
    def test_known_vectors(self):
        # iSCSI test vectors (RFC 3720 appendix shapes)
        assert crc32c(b"") == 0
        assert crc32c(b"123456789") == 0xE3069283
        assert crc32c(b"\x00" * 32) == 0x8A9136AA
        assert crc32c(b"\xff" * 32) == 0x62A8AB43

    def test_chaining(self):
        data = os.urandom(999)
        for cut in (0, 1, 511, 998, 999):
            assert crc32c(data[cut:], crc32c(data[:cut])) == crc32c(data)

    def test_buffer_types(self):
        data = os.urandom(64)
        ref = crc32c(data)
        assert crc32c(bytearray(data)) == ref
        assert crc32c(memoryview(data)) == ref
        assert crc32c(np.frombuffer(data, dtype=np.uint8)) == ref

    def test_pure_python_matches_native(self):
        from byteps_tpu import native as bnative
        from byteps_tpu.comm import transport

        lib = bnative.get_lib()
        if lib is None or not hasattr(lib, "bps_wire_crc32c"):
            pytest.skip("native lib (with crc shim) not built")
        saved = transport._crc_native
        try:
            for n in (0, 1, 7, 8, 9, 63, 64, 1024, 4097):
                data = os.urandom(n)
                transport._crc_native = False  # pure-Python table
                pp = transport.crc32c(data, 5)
                transport._crc_native = None  # re-resolve the fast path
                assert transport.crc32c(data, 5) == pp
        finally:
            transport._crc_native = saved


# --------------------------------------------------------------------------
# codec semantics


class _PipeSock:
    """recv_into over a byte string (EOF after)."""

    def __init__(self, data: bytes) -> None:
        self._b = memoryview(bytes(data))
        self._off = 0

    def recv_into(self, view, nbytes: int = 0) -> int:
        n = nbytes or len(view)
        take = min(n, len(self._b) - self._off)
        if take <= 0:
            return 0
        view[:take] = self._b[self._off : self._off + take]
        self._off += take
        return take


class TestChecksumCodec:
    def test_roundtrip_with_and_without_trace(self):
        for trace in (None, (0x1234, 0x5678)):
            m = Message(Op.PUSH, key=9, payload=b"hello wire", seq=3,
                        cmd=CMD_F32, version=2, flags=1, trace=trace,
                        checksum=True)
            out = recv_message(_PipeSock(m.encode()))
            assert out.op == Op.PUSH and out.payload == b"hello wire"
            assert out.status == 0  # flag consumed, status clean
            assert out.trace == trace

    def test_flag_layout(self):
        m = Message(Op.PUSH, key=9, payload=b"xy", seq=3, checksum=True)
        frame = m.encode()
        assert frame[2] & CHECKSUM_FLAG
        assert len(frame) == HEADER_SIZE + 4 + 2
        (crc,) = struct.unpack_from("!I", frame, HEADER_SIZE)
        assert crc == frame_checksum(None, b"xy") == crc32c(b"xy")
        # with trace: header | trace | crc | payload, crc covers both
        mt = Message(Op.PUSH, key=9, payload=b"xy", seq=3,
                     trace=(7, 8), checksum=True)
        ft = mt.encode()
        assert len(ft) == HEADER_SIZE + 16 + 4 + 2
        (crct,) = struct.unpack_from("!I", ft, HEADER_SIZE + 16)
        assert crct == crc32c(b"xy", crc32c(ft[HEADER_SIZE:HEADER_SIZE + 16]))

    def test_mismatch_raises_after_full_consumption(self):
        """Drop semantics: the corrupted frame raises AFTER its bytes
        are consumed, so the NEXT frame on the stream decodes."""
        bad = _flip(Message(Op.PUSH, key=1, payload=b"abcdef", seq=1,
                            checksum=True).encode(), HEADER_SIZE + 4 + 2)
        good = Message(Op.PULL, key=2, seq=2, checksum=True).encode()
        pipe = _PipeSock(bad + good)
        with pytest.raises(ChecksumError) as ei:
            recv_message(pipe)
        assert ei.value.op == Op.PUSH
        nxt = recv_message(pipe)  # stream still framed
        assert nxt.op == Op.PULL and nxt.seq == 2

    def test_every_covered_region_detected(self):
        m = Message(Op.PUSH, key=1, payload=b"abcdef", seq=1,
                    trace=(0xAA, 0xBB), checksum=True)
        frame = m.encode()
        # trace block, crc field itself, payload — all covered
        for off in (HEADER_SIZE, HEADER_SIZE + 15, HEADER_SIZE + 16,
                    HEADER_SIZE + 19, HEADER_SIZE + 20, len(frame) - 1):
            with pytest.raises(ChecksumError):
                recv_message(_PipeSock(_flip(frame, off)))

    def test_non_verifying_consumer_stays_framed(self):
        """recv_header (the zero-copy fast path's header read) consumes
        the checksum block without verifying — oblivious consumers keep
        framing, the TRACE_FLAG contract."""
        m = Message(Op.PUSH, key=1, payload=b"xyz", seq=5, checksum=True)
        pipe = _PipeSock(m.encode())
        op, status, _f, seq, _k, _c, _v, length = recv_header(pipe)
        assert (op, status, seq, length) == (Op.PUSH, 0, 5, 3)
        buf = bytearray(3)
        assert pipe.recv_into(memoryview(buf)) == 3
        assert bytes(buf) == b"xyz"

    def test_env_knob_stamps_data_plane_only(self, monkeypatch):
        monkeypatch.setenv("BYTEPS_WIRE_CHECKSUM", "1")
        assert Message(Op.PUSH, key=1, payload=b"x").encode()[2] & CHECKSUM_FLAG
        assert Message(Op.MIGRATE_STATE, key=1).encode()[2] & CHECKSUM_FLAG
        # control frames stay byte-identical
        for op in (Op.REGISTER, Op.ADDRBOOK, Op.BARRIER, Op.PING,
                   Op.SHUTDOWN, Op.QUERY):
            assert not Message(op, key=1).encode()[2] & CHECKSUM_FLAG
        monkeypatch.delenv("BYTEPS_WIRE_CHECKSUM")
        assert not Message(Op.PUSH, key=1, payload=b"x").encode()[2] & CHECKSUM_FLAG


# --------------------------------------------------------------------------
# chaos payload-corrupt fault


class _SinkSock:
    def __init__(self) -> None:
        self.frames = []

    def sendall(self, data) -> None:
        self.frames.append(bytes(data))


class TestChaosPayloadCorrupt:
    def _sock(self, **kw):
        reset_fault_budget(kw.pop("budget", None))
        inner = _SinkSock()
        cs = ChaosSocket(inner, ChaosParams(seed=3, **kw), conn_index=0)
        return cs, inner

    def test_single_bit_flip_past_header(self):
        counters().reset()
        cs, inner = self._sock(payload_corrupt=1.0)
        frame = Message(Op.PUSH, key=1, payload=bytes(64), seq=1,
                        checksum=True).encode()
        cs.sendall(frame)
        assert len(inner.frames) == 1
        sent = inner.frames[0]
        assert len(sent) == len(frame)
        assert sent[:HEADER_SIZE] == frame[:HEADER_SIZE]  # header intact
        diff = [i for i in range(len(frame)) if sent[i] != frame[i]]
        assert len(diff) == 1 and diff[0] >= HEADER_SIZE
        xor = sent[diff[0]] ^ frame[diff[0]]
        assert xor and (xor & (xor - 1)) == 0  # exactly one bit
        assert counters().get("chaos_payload_corrupt") == 1
        # ...and the mutated frame is exactly what the verifier rejects
        with pytest.raises(ChecksumError):
            recv_message(_PipeSock(sent))

    def test_header_only_frame_passes_untouched(self):
        counters().reset()
        cs, inner = self._sock(payload_corrupt=1.0, budget=1)
        frame = Message(Op.PULL, key=1, seq=1).encode()  # 32 bytes
        cs.sendall(frame)
        assert inner.frames == [frame]
        assert counters().get("chaos_payload_corrupt") == 0
        # the budget was NOT spent on the no-op: the next payload frame
        # still gets its flip
        cs.sendall(Message(Op.PUSH, key=1, payload=b"abcd", seq=2).encode())
        assert counters().get("chaos_payload_corrupt") == 1

    def test_composes_with_op_targeting_and_budget(self):
        counters().reset()
        cs, inner = self._sock(payload_corrupt=1.0,
                               ops=frozenset({int(Op.FUSED)}), budget=1)
        push = Message(Op.PUSH, key=1, payload=b"abcd", seq=1).encode()
        fused = Message(Op.FUSED, key=1, seq=2,
                        payload=encode_fused_push(
                            [(1, CMD_F32, 1, b"wxyz")])).encode()
        cs.sendall(push)     # untargeted op: passes, no RNG roll
        cs.sendall(fused)    # targeted: flipped (budget 1 → spent)
        cs.sendall(fused)    # budget spent: passes
        assert inner.frames[0] == push
        assert inner.frames[1] != fused
        assert inner.frames[2] == fused
        assert counters().get("chaos_payload_corrupt") == 1
        reset_fault_budget()

    def test_seeded_flip_is_deterministic(self):
        outs = []
        for _ in range(2):
            cs, inner = self._sock(payload_corrupt=1.0)
            cs.sendall(Message(Op.PUSH, key=1, payload=bytes(128),
                               seq=1).encode())
            outs.append(inner.frames[0])
        assert outs[0] == outs[1]


def test_wire_fuzz_smoke():
    """Tier-1 wiring for tools/wire_fuzz.py beside the other guards: a
    seeded pass over every codec must reject every mutation."""
    spec = importlib.util.spec_from_file_location(
        "wire_fuzz", os.path.join(REPO, "tools", "wire_fuzz.py")
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault("wire_fuzz", mod)
    spec.loader.exec_module(mod)
    stats = mod.run_fuzz(seed=7, flips=240, truncations=120)
    assert stats["flips"] >= 240 and stats["truncations"] >= 120
    assert stats["baseline_silent"] > 0
    # the lossless leg ran: truncated/corrupted containers failed
    # closed and every checksummed flip was a ChecksumError (CRC is
    # verified over the compressed bytes BEFORE the container decode)
    assert stats["lossless_truncations"] > 0
    assert stats["lossless_flips_crc"] > 0
    assert stats["lossless_structural"] > 0


# --------------------------------------------------------------------------
# verify-and-heal, wire level (both engines × fused × codec)


def _init_key(socks_flags, key: int, n: int) -> None:
    payload = struct.pack("!QI", n, int(DataType.FLOAT32))
    for i, (sock, flag) in enumerate(socks_flags):
        send_message(sock, Message(Op.INIT, key=key, seq=100 + i, flags=flag,
                                   payload=payload))
    for sock, _ in socks_flags:
        assert recv_message(sock).op == Op.INIT


def _register_codec(sock, key: int, kwargs: dict, seq: int) -> None:
    body = "\n".join(f"{k}={v}" for k, v in sorted(kwargs.items())).encode()
    send_message(sock, Message(Op.REGISTER_COMPRESSOR, key=key, seq=seq,
                               payload=body))
    assert recv_message(sock).op == Op.REGISTER_COMPRESSOR


def _ck_fails(snap: dict) -> int:
    return snap.get("wire_checksum_fail", 0) + snap.get(
        "native_checksum_fail", 0
    )


def _expect_silence(sock, budget: float = 0.8) -> None:
    """The corrupted frame must be DROPPED: no reply, no teardown."""
    sock.settimeout(budget)
    try:
        recv_message(sock)
    except (socket.timeout, TimeoutError):
        sock.settimeout(15)
        return
    raise AssertionError("corrupted frame was answered")


class TestVerifyAndHeal:
    """Wire-level: a corrupted push is dropped before the sum core, the
    clean resend (the deadline/retry analogue) sums exactly once, a
    replay dedupes, and every pull is bitwise-stable."""

    @pytest.mark.parametrize(("engine", "stripes"), ENGINE_STRIPES,
                             ids=ENGINE_STRIPES_IDS)
    @pytest.mark.parametrize("fused", [False, True],
                             ids=["unfused", "fused"])
    @pytest.mark.parametrize("codec", ["raw", "onebit"])
    def test_corrupted_push_retries_and_dedupes(self, engine, stripes,
                                                fused, codec, monkeypatch):
        require_engine(engine)
        set_stripes(monkeypatch, stripes)
        monkeypatch.setenv("BYTEPS_WIRE_CHECKSUM", "1")
        counters().reset()
        KEY, N = 11, 64
        srv = make_ps_server(engine, Config(num_worker=1, num_server=1))
        if engine != "native":
            srv.start(register=False)
        try:
            sock = connect(srv.host, srv.port)
            sock.settimeout(15)
            _init_key([(sock, 1)], KEY, N)
            g = np.arange(N, dtype=np.float32) - 17.5
            if codec == "onebit":
                from byteps_tpu.compression.registry import create_compressor

                kwargs = {"byteps_compressor_type": "onebit"}
                _register_codec(sock, KEY, kwargs, seq=5)
                comp = create_compressor(dict(kwargs), N, server=False)
                payload = comp.compress(g.copy())
                cmd = CMD_COMP
            else:
                payload = g.tobytes()
                cmd = CMD_F32

            def push_frame(seq):
                if fused:
                    return Message(
                        Op.FUSED, key=KEY, seq=seq, flags=1, cmd=2,
                        payload=encode_fused_push([(KEY, cmd, 1, payload)]),
                    )
                return Message(Op.PUSH, key=KEY, seq=seq, flags=1, cmd=cmd,
                               version=1, payload=payload)

            # 1: the corrupted frame — valid CRC stamp, then one payload
            # byte flipped in transit (what the chaos van injects)
            frame = push_frame(1).encode()
            assert frame[2] & CHECKSUM_FLAG
            sock.sendall(_flip(frame, len(frame) - 3))
            _expect_silence(sock)
            snap = counters().snapshot()
            assert _ck_fails(snap) == 1, snap
            # the ledger was never touched: no dedupe recorded yet
            assert snap.get("push_dedup", 0) == 0
            assert snap.get("native_push_dedup", 0) == 0

            # 2: the clean resend (same seq — the retry) sums once
            send_message(sock, push_frame(1))
            ack = recv_message(sock)
            assert ack.seq == 1 and ack.status == 0
            if fused:
                pull1 = [p for _k, _v, p in decode_fused_reply(ack.payload)][0]
            else:
                send_message(sock, Message(Op.PULL, key=KEY, seq=2, cmd=cmd,
                                           version=1))
                pull1 = recv_message(sock).payload
            if codec == "raw":
                np.testing.assert_array_equal(
                    np.frombuffer(pull1, dtype=np.float32), g
                )

            # 3: replay the SAME round again — the exactly-once ledger
            # dedupes, the published bytes must not move
            send_message(sock, push_frame(3))
            ack2 = recv_message(sock)
            assert ack2.status == 0
            if fused:
                pull2 = [p for _k, _v, p in decode_fused_reply(ack2.payload)][0]
            else:
                send_message(sock, Message(Op.PULL, key=KEY, seq=4, cmd=cmd,
                                           version=1))
                pull2 = recv_message(sock).payload
            assert bytes(pull1) == bytes(pull2)
            snap = counters().snapshot()
            dedupe = (snap.get("push_dedup", 0)
                      + snap.get("native_push_dedup", 0))
            assert dedupe >= 1, snap
            close_socket(sock)
        finally:
            srv.stop()

    @pytest.mark.parametrize(("engine", "stripes"),
                             [("python", 0), ("native", 4)],
                             ids=["python", "native-s4"])
    def test_conn_limit_quarantines_then_fresh_dial_serves(
            self, engine, stripes, monkeypatch):
        """Escalation: BYTEPS_CHECKSUM_CONN_LIMIT mismatches on one
        connection drop it (the receiver's quarantine); a fresh dial —
        what connection revival does — serves normally."""
        require_engine(engine)
        set_stripes(monkeypatch, stripes)
        monkeypatch.setenv("BYTEPS_WIRE_CHECKSUM", "1")
        monkeypatch.setenv("BYTEPS_CHECKSUM_CONN_LIMIT", "3")
        counters().reset()
        KEY, N = 7, 16
        srv = make_ps_server(engine, Config(num_worker=1, num_server=1))
        if engine != "native":
            srv.start(register=False)
        try:
            sock = connect(srv.host, srv.port)
            sock.settimeout(15)
            _init_key([(sock, 1)], KEY, N)
            g = np.ones(N, dtype=np.float32)
            frame = Message(Op.PUSH, key=KEY, seq=1, flags=1, cmd=CMD_F32,
                            version=1, payload=g.tobytes()).encode()
            for _ in range(3):
                sock.sendall(_flip(frame, len(frame) - 1))
            # the third mismatch trips the limit: the server closes the
            # conn — the next read sees EOF, not silence
            sock.settimeout(5)
            with pytest.raises((ConnectionError, OSError)):
                while True:
                    recv_message(sock)
            snap = counters().snapshot()
            assert _ck_fails(snap) == 3, snap
            drops = (snap.get("wire_checksum_conn_drop", 0)
                     + snap.get("native_checksum_conn_drop", 0))
            assert drops == 1, snap
            close_socket(sock)
            # revival: a fresh dial works and the ledger is clean
            sock2 = connect(srv.host, srv.port)
            sock2.settimeout(15)
            send_message(sock2, Message(Op.PUSH, key=KEY, seq=9, flags=1,
                                        cmd=CMD_F32, version=1,
                                        payload=g.tobytes()))
            assert recv_message(sock2).status == 0
            send_message(sock2, Message(Op.PULL, key=KEY, seq=10, cmd=CMD_F32,
                                        version=1))
            np.testing.assert_array_equal(
                np.frombuffer(recv_message(sock2).payload, dtype=np.float32),
                g,
            )
            close_socket(sock2)
        finally:
            srv.stop()


# --------------------------------------------------------------------------
# client-side verification (recv lanes, both client implementations)


def _stub_client_and_conn(sock):
    """A minimal PSClient + _ServerConn pair around one end of a
    socketpair — just enough surface for _recv_loop."""
    from byteps_tpu.comm.ps_client import PSClient, _ServerConn

    client = PSClient.__new__(PSClient)
    client._stop = threading.Event()
    client.zero_copy_pulls = 0
    sc = _ServerConn.__new__(_ServerConn)
    sc.sock = sock
    sc.send_lock = threading.Lock()
    sc.stripes = [(sock, sc.send_lock)]
    sc.cb_lock = threading.Lock()
    sc.callbacks = {}
    sc.sinks = {}
    sc.next_seq = 0
    sc.recv_thread = None
    sc.dead = False
    sc._live_lanes = 1
    sc.server_label = "0"
    sc._ck_fails = 0
    return client, sc


class TestClientRecvVerify:
    def _reply(self, seq, payload, op=Op.FUSED):
        return Message(op, key=1, payload=payload, seq=seq,
                       checksum=True).encode()

    def test_corrupted_reply_dropped_before_demux_then_refetch_lands(self):
        """A corrupted fused multi-key reply must NOT fire the seq
        callback (no double-publish path exists: the demux never saw
        it); the re-fetched clean reply lands normally."""
        counters().reset()
        a, b = socket.socketpair()
        client, sc = _stub_client_and_conn(a)
        got = []
        done = threading.Event()
        seq = sc.alloc_seq(lambda m: (got.append(m), done.set()))
        t = threading.Thread(target=client._recv_loop, args=(sc, a),
                             daemon=True)
        t.start()
        reply = encode_fused_reply([(1, 1, b"abcd"), (2, 1, b"wxyz")])
        frame = self._reply(seq, reply)
        b.sendall(_flip(frame, len(frame) - 2))  # corrupted in transit
        time.sleep(0.3)
        assert not done.is_set()                 # demux never fired
        assert sc.pop_cb(seq) is not None        # cb still registered...
        sc.callbacks[seq] = lambda m: (got.append(m), done.set())  # restore
        snap = counters().snapshot_labeled().get("wire_checksum_fail", {})
        assert any(dict(k).get("side") == "client" and
                   dict(k).get("op") == "FUSED" for k in snap), snap
        b.sendall(frame)                         # the re-fetch
        assert done.wait(5)
        assert got[0] is not None and got[0].payload == reply
        client._stop.set()
        close_socket(b)
        close_socket(a)
        t.join(timeout=5)

    def test_corrupted_resync_state_reply_dropped(self):
        from byteps_tpu.comm.transport import encode_resync_state

        counters().reset()
        a, b = socket.socketpair()
        client, sc = _stub_client_and_conn(a)
        got = []
        seq = sc.alloc_seq(got.append)
        t = threading.Thread(target=client._recv_loop, args=(sc, a),
                             daemon=True)
        t.start()
        state = encode_resync_state(
            {5: {"store_version": 4, "seen": 3, "recv_count": 1,
                 "init": True}}
        )
        frame = self._reply(seq, state, op=Op.RESYNC_STATE)
        b.sendall(_flip(frame, HEADER_SIZE + 4 + 10))
        time.sleep(0.3)
        assert got == []  # dropped before the demux
        snap = counters().snapshot_labeled().get("wire_checksum_fail", {})
        assert any(dict(k).get("op") == "RESYNC_STATE" for k in snap), snap
        client._stop.set()
        close_socket(b)
        close_socket(a)
        t.join(timeout=5)

    def test_conn_limit_poisons_connection_for_revival(self, monkeypatch):
        """BYTEPS_CHECKSUM_CONN_LIMIT mismatches on the client lane end
        the recv loop — the connection dies the same way a transport
        failure kills it, so the existing revival machinery owns it."""
        monkeypatch.setenv("BYTEPS_CHECKSUM_CONN_LIMIT", "2")
        counters().reset()
        a, b = socket.socketpair()
        client, sc = _stub_client_and_conn(a)
        got = []
        seq = sc.alloc_seq(got.append)
        t = threading.Thread(target=client._recv_loop, args=(sc, a),
                             daemon=True)
        t.start()
        frame = self._reply(seq, b"payload-bytes", op=Op.PULL)
        b.sendall(_flip(frame, len(frame) - 1))
        b.sendall(_flip(frame, len(frame) - 2))
        t.join(timeout=5)
        assert not t.is_alive()  # the lane exited at the limit
        # the loop's finally drained the pending cb with None (dead conn)
        assert got == [None]
        assert sc.dead
        assert counters().get("wire_checksum_conn_drop") == 1
        close_socket(b)

    def test_zero_copy_sink_verified(self):
        """A corrupted zero-copy pull (payload received INTO the
        caller's buffer) is still verified and dropped; the retried
        response overwrites the garbage before the caller wakes."""
        counters().reset()
        a, b = socket.socketpair()
        client, sc = _stub_client_and_conn(a)
        sink = np.zeros(8, dtype=np.float32)
        got = []
        done = threading.Event()
        seq = sc.alloc_seq(lambda m: (got.append(m), done.set()),
                           sink=memoryview(sink).cast("B"))
        t = threading.Thread(target=client._recv_loop, args=(sc, a),
                             daemon=True)
        t.start()
        want = np.arange(8, dtype=np.float32)
        frame = self._reply(seq, want.tobytes(), op=Op.PULL)
        b.sendall(_flip(frame, len(frame) - 4))
        time.sleep(0.3)
        assert not done.is_set()
        assert client.zero_copy_pulls == 0  # rejected frames don't count
        b.sendall(frame)
        assert done.wait(5)
        np.testing.assert_array_equal(sink, want)
        assert client.zero_copy_pulls == 1
        client._stop.set()
        close_socket(b)
        close_socket(a)
        t.join(timeout=5)


class TestNativeClientVerify:
    """The C++ recv lanes verify replies before the seq demux: a
    corrupted reply is dropped in C++ (pending entry survives), Python
    is notified through the op=-3 record, and the clean retry lands."""

    def _fake_server(self):
        lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        lsock.bind(("127.0.0.1", 0))
        lsock.listen(4)
        return lsock, lsock.getsockname()[1]

    def _native_conn(self, port):
        from byteps_tpu.comm.ps_client import _NativeServerConn
        from byteps_tpu.native import get_lib

        lib = get_lib()
        if lib is None or not hasattr(lib, "bpsc_drain"):
            pytest.skip("native client library unavailable")
        return _NativeServerConn("127.0.0.1", port, streams=1)

    def test_corrupted_reply_dropped_then_clean_lands(self):
        counters().reset()
        lsock, port = self._fake_server()
        conn = None
        try:
            conn = self._native_conn(port)
            peer, _ = lsock.accept()
            got = []
            done = threading.Event()
            seq = conn.alloc_seq(lambda m: (got.append(m), done.set()))
            frame = Message(Op.PULL, key=3, payload=b"pull-bytes",
                            seq=seq, checksum=True).encode()
            peer.sendall(_flip(frame, len(frame) - 3))
            time.sleep(0.4)
            assert not done.is_set()
            snap = counters().snapshot_labeled().get("wire_checksum_fail", {})
            assert any(dict(k).get("side") == "client" and
                       dict(k).get("op") == "PULL" for k in snap), snap
            peer.sendall(frame)
            assert done.wait(5)
            assert got[0] is not None and got[0].payload == b"pull-bytes"
            close_socket(peer)
        finally:
            if conn is not None:
                conn.close_all()
            close_socket(lsock)

    def test_conn_limit_poisons_native_connection(self, monkeypatch):
        monkeypatch.setenv("BYTEPS_CHECKSUM_CONN_LIMIT", "2")
        counters().reset()
        lsock, port = self._fake_server()
        conn = None
        try:
            conn = self._native_conn(port)  # limit read at create
            peer, _ = lsock.accept()
            got = []
            done = threading.Event()
            seq = conn.alloc_seq(lambda m: (got.append(m), done.set()))
            frame = Message(Op.PULL, key=3, payload=b"pull-bytes",
                            seq=seq, checksum=True).encode()
            peer.sendall(_flip(frame, len(frame) - 3))
            peer.sendall(_flip(frame, len(frame) - 5))
            # the second mismatch trips the limit: the lane dies and the
            # drain fails the pending request (cb(None)) — exactly the
            # dead-conn shape the revival machinery heals
            assert done.wait(5)
            assert got == [None]
            assert conn.dead
            # the Python mirror recorded the quarantine exactly once
            assert counters().get("wire_checksum_conn_drop") == 1
            close_socket(peer)
        finally:
            if conn is not None:
                conn.close_all()
            close_socket(lsock)


# --------------------------------------------------------------------------
# end-to-end heals


class TestEndToEndHeal:
    def _cluster_env(self, monkeypatch, sched_port):
        for k, v in {
            "DMLC_PS_ROOT_URI": "127.0.0.1",
            "DMLC_PS_ROOT_PORT": str(sched_port),
            "DMLC_NUM_WORKER": "1",
            "DMLC_NUM_SERVER": "1",
            "BYTEPS_FORCE_DISTRIBUTED": "1",
            "BYTEPS_HEARTBEAT_INTERVAL": "0.2",
            "BYTEPS_RPC_DEADLINE_S": "0.3",
            "BYTEPS_RPC_RETRIES": "3",
            "BYTEPS_RPC_BACKOFF_S": "0.05",
            "BYTEPS_INIT_DEADLINE_S": "1.0",
            "BYTEPS_CONNECT_RETRY_S": "0.2",
            "BYTEPS_WIRE_CHECKSUM": "1",
        }.items():
            monkeypatch.setenv(k, v)

    def test_corrupted_fused_frame_heals_bitwise(self, monkeypatch):
        """One seeded payload flip on the first FUSED frame: the server
        drops it before the sum core, the deadline retry re-sends, the
        pull is bitwise — and nothing double-publishes (the corrupted
        frame never reached the ledger)."""
        from byteps_tpu.comm.chaos import reset_conn_indices
        from byteps_tpu.comm.rendezvous import Scheduler
        from byteps_tpu.server.server import PSServer

        monkeypatch.setenv("BYTEPS_VAN", "chaos:tcp")
        monkeypatch.setenv("BYTEPS_CHAOS_SEED", "5")
        monkeypatch.setenv("BYTEPS_CHAOS_PAYLOAD_CORRUPT", "1.0")
        monkeypatch.setenv("BYTEPS_CHAOS_OPS", "FUSED")
        monkeypatch.setenv("BYTEPS_CHAOS_FAULT_BUDGET", "1")
        monkeypatch.setenv("BYTEPS_FUSION_THRESHOLD", "65536")
        counters().reset()
        reset_fault_budget()
        reset_conn_indices()
        sched = Scheduler(num_workers=1, num_servers=1, host="127.0.0.1")
        sched.start()
        self._cluster_env(monkeypatch, sched.port)
        srv = PSServer(Config.from_env())
        threading.Thread(target=srv.start, daemon=True).start()

        import byteps_tpu as bps

        try:
            bps.init()
            rng = np.random.default_rng(1)
            for _step in range(3):
                x = rng.standard_normal(257).astype(np.float32)
                out = bps.push_pull(x, name="integrity.fused", average=False)
                np.testing.assert_array_equal(np.asarray(out), x)
            snap = bps.get_robustness_counters()
            assert snap.get("chaos_payload_corrupt", 0) == 1, snap
            assert snap.get("wire_checksum_fail", 0) == 1, snap
            assert snap.get("fused_frames", 0) >= 3, snap
            assert snap.get("rpc_giveup", 0) == 0, snap
            assert snap.get("degraded_jobs", 0) == 0, snap
        finally:
            bps.shutdown()
            srv.stop()
            sched.stop()
            reset_fault_budget()

    def test_corrupted_resync_state_fails_heal_cleanly(self, monkeypatch):
        """Every PUSH and every RESYNC_STATE corrupted forever: the
        give-up's in-place heal cannot complete (its state replies never
        verify), so it fails CLEANLY — resync_giveup, the key marked
        for re-init, a DegradedError to the caller — instead of
        training on a corrupt ledger snapshot or hanging."""
        from byteps_tpu.common.types import DegradedError
        from byteps_tpu.comm.chaos import reset_conn_indices
        from byteps_tpu.comm.rendezvous import Scheduler
        from byteps_tpu.server.server import PSServer

        monkeypatch.setenv("BYTEPS_VAN", "chaos:tcp")
        monkeypatch.setenv("BYTEPS_CHAOS_SEED", "5")
        monkeypatch.setenv("BYTEPS_CHAOS_PAYLOAD_CORRUPT", "1.0")
        monkeypatch.setenv("BYTEPS_CHAOS_OPS", "PUSH,RESYNC_STATE")
        monkeypatch.setenv("BYTEPS_CHAOS_FAULT_BUDGET", "-1")
        monkeypatch.setenv("BYTEPS_CHECKSUM_CONN_LIMIT", "0")
        monkeypatch.setenv("BYTEPS_RESYNC_DEADLINE_S", "1.0")
        monkeypatch.setenv("BYTEPS_DEGRADED_STEP_RETRIES", "0")
        counters().reset()
        reset_fault_budget()
        reset_conn_indices()
        sched = Scheduler(num_workers=1, num_servers=1, host="127.0.0.1")
        sched.start()
        self._cluster_env(monkeypatch, sched.port)
        srv = PSServer(Config.from_env())
        threading.Thread(target=srv.start, daemon=True).start()

        import byteps_tpu as bps

        try:
            bps.init()
            x = np.full(64, 2.5, dtype=np.float32)
            with pytest.raises(DegradedError):
                bps.push_pull(x, name="integrity.resync", average=False)
            snap = bps.get_robustness_counters()
            assert snap.get("resync_attempt", 0) >= 1, snap
            assert snap.get("resync_giveup", 0) >= 1, snap
            assert snap.get("wire_checksum_fail", 0) >= 1, snap
            labeled = counters().snapshot_labeled().get(
                "wire_checksum_fail", {}
            )
            assert any(dict(k).get("op") == "RESYNC_STATE"
                       for k in labeled), labeled
            # clean failure TO the re-init path: the key is marked
            from byteps_tpu.core.state import get_state

            assert "integrity.resync" in get_state().engine._reinit_names
        finally:
            bps.shutdown()
            srv.stop()
            sched.stop()
            reset_fault_budget()


# --------------------------------------------------------------------------
# observability bindings


class TestObservability:
    def test_corruption_storm_rule(self):
        from byteps_tpu.core.flightrec import _rule_corruption_storm

        fire = _rule_corruption_storm(None, {"events": {
            "wire_checksum_fail": 5, "chaos_payload_corrupt": 5,
        }})
        assert fire == {"checksum_fails": 5, "conn_drops": 0, "injected": 5}
        # a single mismatch is the retry machinery's job, not a storm
        assert _rule_corruption_storm(None, {"events": {
            "wire_checksum_fail": 1,
        }}) is None
        # ...but any conn-limit quarantine is
        fire = _rule_corruption_storm(None, {"events": {
            "wire_checksum_fail": 1, "wire_checksum_conn_drop": 1,
        }})
        assert fire is not None and fire["conn_drops"] == 1
        # the C++ engine's rejections (provider-merged native_* deltas)
        # arm the rule the same way
        fire = _rule_corruption_storm(None, {"events": {
            "native_checksum_fail": 4,
        }})
        assert fire is not None and fire["checksum_fails"] == 4
        assert _rule_corruption_storm(None, {"events": {
            "native_checksum_conn_drop": 1,
        }}) is not None
        assert _rule_corruption_storm(None, {"events": {}}) is None

    def test_wire_checksum_fail_rides_flight_events(self):
        from byteps_tpu.core.flightrec import EVENT_COUNTERS

        for name in ("wire_checksum_fail", "wire_checksum_conn_drop",
                     "chaos_payload_corrupt"):
            assert name in EVENT_COUNTERS

    def test_doctor_wire_corruption_rule(self):
        spec = importlib.util.spec_from_file_location(
            "bps_doctor", os.path.join(REPO, "tools", "bps_doctor.py")
        )
        doctor = importlib.util.module_from_spec(spec)
        sys.modules.setdefault("bps_doctor", doctor)
        spec.loader.exec_module(doctor)
        doctor = sys.modules["bps_doctor"]
        v = doctor.View()
        v.counters = {"wire_checksum_fail": 12.0,
                      "wire_checksum_conn_drop": 1.0}
        v.labeled = {"wire_checksum_fail": [
            ({"side": "client", "op": "PULL", "server": "1"}, 9.0),
            ({"side": "server", "op": "PUSH"}, 3.0),
        ]}
        findings = doctor.diagnose(v)
        rules = [f.rule for f in findings]
        assert "wire_corruption" in rules, rules
        f = findings[rules.index("wire_corruption")]
        assert any("server 1" in ev for ev in f.evidence), f.evidence
        # silent when nothing failed
        assert doctor._r_wire_corruption(doctor.View()) is None
