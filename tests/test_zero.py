"""ZeRO-1 sharded-optimizer tests: must match plain DDP training exactly
while holding only 1/N of the optimizer state per member."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from byteps_tpu.optim import build_data_parallel_step, build_zero1_step


def _toy(n=256, d=16, seed=0):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=(d, 1)).astype(np.float32)
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = x @ w
    return jnp.asarray(x), jnp.asarray(y)


def _loss(params, batch):
    x, y = batch
    return jnp.mean((x @ params["w"] + params["b"] - y) ** 2)


class TestZero1:
    def test_matches_ddp_sgd(self, mesh8):
        x, y = _toy()
        params0 = {"w": jnp.zeros((16, 1)), "b": jnp.zeros((1,))}

        tx = optax.sgd(0.1)
        ddp = build_data_parallel_step(_loss, tx, mesh=mesh8, donate=False)
        p_ref, s_ref = params0, jax.jit(tx.init)(params0)
        for _ in range(10):
            p_ref, s_ref, loss_ref = ddp(p_ref, s_ref, (x, y))

        init_fn, step = build_zero1_step(_loss, optax.sgd(0.1), mesh=mesh8, donate=False)
        p_z, s_z = params0, init_fn(params0)
        for _ in range(10):
            p_z, s_z, loss_z = step(p_z, s_z, (x, y))

        np.testing.assert_allclose(np.asarray(p_z["w"]), np.asarray(p_ref["w"]), rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(float(loss_z), float(loss_ref), rtol=1e-5)

    def test_matches_ddp_adam(self, mesh8):
        x, y = _toy(seed=1)
        params0 = {"w": jnp.zeros((16, 1)), "b": jnp.zeros((1,))}

        tx = optax.adam(0.05)
        ddp = build_data_parallel_step(_loss, tx, mesh=mesh8, donate=False)
        p_ref, s_ref = params0, jax.jit(tx.init)(params0)
        for _ in range(10):
            p_ref, s_ref, _ = ddp(p_ref, s_ref, (x, y))

        init_fn, step = build_zero1_step(_loss, optax.adam(0.05), mesh=mesh8, donate=False)
        p_z, s_z = params0, init_fn(params0)
        for _ in range(10):
            p_z, s_z, _ = step(p_z, s_z, (x, y))

        np.testing.assert_allclose(np.asarray(p_z["w"]), np.asarray(p_ref["w"]), rtol=1e-4, atol=1e-5)

    def test_state_is_sharded(self, mesh8):
        """Adam's m/v live sharded: global state leaves have leading dim 8
        (one shard per member), each 1/8 of the padded flat params."""
        params0 = {"w": jnp.zeros((16, 1)), "b": jnp.zeros((1,))}
        init_fn, _ = build_zero1_step(_loss, optax.adam(0.05), mesh=mesh8, donate=False)
        st = init_fn(params0)
        mu = st[0].mu  # ScaleByAdamState
        n_params = 16 * 1 + 1
        padded = n_params + ((-n_params) % 8)
        assert mu.shape == (8, padded // 8)