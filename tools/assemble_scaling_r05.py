"""Assemble SCALING_r05.json from run_scaling_r05.sh's cell lines."""

import json
import sys

cells_path = sys.argv[1] if len(sys.argv) > 1 else "/tmp/scaling_r05_cells.jsonl"
out_path = sys.argv[2] if len(sys.argv) > 2 else "SCALING_r05.json"

cells = {}
failed = []
with open(cells_path) as f:
    for line in f:
        d = json.loads(line)
        if d.get("failed"):
            failed.append(d["label"])
        else:
            cells[d["label"]] = d["result"]
if failed:
    raise SystemExit(f"refusing to assemble: failed cells {failed} "
                     f"(see the run log); re-run those cells first")

REQUIRED = ["native-shm-scaledsrv", "native-tcp-scaledsrv",
            "native-shm-2srv", "native-tcp-2srv",
            "python-shm-2srv", "python-tcp-2srv"]
missing = [c for c in REQUIRED if c not in cells]
if missing:
    raise SystemExit(f"missing cells: {missing}")

# median the headline cell's samples (by 8-worker aggregate throughput)
head_labels = ["native-shm-2srv", "native-shm-2srv-rep2", "native-shm-2srv-rep3"]
head_runs = [cells[x] for x in head_labels if x in cells]
head_runs.sort(key=lambda r: r["extra"]["aggregate_mb_per_s"]["8"])
headline = head_runs[len(head_runs) // 2]
agg8 = [r["extra"]["aggregate_mb_per_s"]["8"] for r in head_runs]

configs = []
for label in REQUIRED:
    r = headline if label == "native-shm-2srv" else cells[label]
    e = r["extra"]
    configs.append({
        "label": label,
        "engine": e["engine"],
        "van": e["van"],
        "servers": e["servers"],
        "aggregate_mb_per_s": e["aggregate_mb_per_s"],
        "round_time_s": e["round_time_s"],
        "retention_vs_1w": e["retention"],
        **({"reps": len(head_runs), "rep_agg8_mb_per_s": agg8}
           if label == "native-shm-2srv" else {}),
    })

ret8 = headline["extra"]["retention"]["8"]
scaled_shm8 = cells["native-shm-scaledsrv"]["extra"]["aggregate_mb_per_s"]["8"]
out = {
    "metric": "pushpull_throughput_retention_multiproc",
    "definition": (
        "aggregate PS-plane MB/s at N subprocess workers vs 1 worker on a "
        "1-CPU-core loopback fake cluster (chip watcher paused). N workers "
        "push N x the bytes on a FIXED cpu budget, so flat (1.0) means the "
        "protocol adds no superlinear overhead as the cluster grows; on "
        "real multi-host hardware (per-node CPUs) this lower-bounds the "
        "reference's scaling-efficiency metric (~90% @ 256 GPUs, "
        f"README.md:38-46). The headline cell is the median of "
        f"{len(head_runs)} runs."
    ),
    "payload_mbytes_per_worker": 4.0,
    "rounds": 8,
    "headline": {
        "config": "native-shm-2srv (2 fixed servers, 512KB rings)",
        "retention_8w": ret8,
        "aggregate_mb_per_s": headline["extra"]["aggregate_mb_per_s"],
    },
    "r5_findings": {
        "ring_size": (
            "The r4 shm-slower-than-tcp inversion was ring working-set "
            "size: 16MB/direction rings across 64 worker-server "
            "connections cycle ~2GB of wrap-around pages through one "
            "core's cache/TLB. Default now 512KB (BYTEPS_SHM_RING_BYTES): "
            "the 8w scaled-servers cell went from 274 MB/s (r4) to "
            f"{scaled_shm8:.0f} MB/s, and even single-pair 8MB bulk "
            "gained ~8% (2979 vs 2762 MB/s, van_bench). Payloads larger "
            "than the ring stream through it, so capacity bought nothing."
        ),
        "server_topology": (
            "The remaining superlinear term was server-process count: "
            "the r4 matrix scaled servers WITH workers (the reference's "
            "multi-host recommendation), so the 8w cell ran 17 processes "
            "on one core and paid context-switch + connection overhead "
            "that grows with the topology. With the per-core-realistic 2 "
            f"fixed servers the 8w retention is {ret8:.2f} (median of "
            f"{len(head_runs)}; reps {sorted(agg8)}) vs ~0.5 scaled. On "
            "real hardware every server has its own CPUs; both shapes "
            "are recorded."
        ),
        "memcpy_bound": (
            "This box moves 12.8 GB/s single-core memcpy (12.7 GB/s f32 "
            "sum-into). The 1-worker shm cell already runs at ~80% of "
            "that bound counting the data plane's byte-moves "
            "(ring write + ring read + sum + response ring + sink); the "
            "8w scaled-servers residual is bandwidth-utilization loss to "
            "context switching across 17 processes, not protocol bytes."
        ),
    },
    "configs": configs,
    "prior_rounds": {"r4_headline_8w": {
        "native-shm-scaledsrv": 0.3424, "native-tcp-scaledsrv": 0.5313}},
}
with open(out_path, "w") as f:
    json.dump(out, f, indent=1)
print(json.dumps({
    "headline_retention_8w": ret8,
    "cells": {c["label"]: c["retention_vs_1w"]["8"] for c in configs},
}))
