#!/usr/bin/env python
"""autotune_bench — A/B of the adaptive control plane on a skewed load
(docs/autotune.md "Demo recipe").

The scenario the hot-key rebalance policy exists for: one server is
slow (here: a chaos-shaped link — every PUSH/PULL frame to its port
eats a deterministic delay, the in-process stand-in for a sick NIC or
an overloaded box) AND owns most of the working set.  Phase A trains
with ``BYTEPS_AUTOTUNE=0``: every round pays the slow server for most
keys, forever.  Phase B trains with ``BYTEPS_AUTOTUNE=1``: the tuner
sees the load imbalance in the servers' hot-key reports, moves the hot
keys to the healthy server through the live migration plane (no
re-init, pulls bitwise through the move), and the measured window runs
on the rebalanced placement.

Each phase runs in a fresh subprocess (chaos + autotune knobs are
process-wide env).  Writes ``AUTOTUNE_BENCH_r01.json``-style output:
steps/s per phase, the speedup ratio, and the tuner's action log.

Usage:
    python tools/autotune_bench.py --out AUTOTUNE_BENCH_r01.json
    python tools/autotune_bench.py --phase on        # (internal) one phase
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
)

HOT_KEYS = 6        # keys homed on the slow server
COLD_KEYS = 2       # keys homed on the healthy server
DIM = 2048          # floats per key
DELAY_MS = 3        # per-frame chaos delay on the slow server's port
MEASURE_ROUNDS = 40
WARMUP_ROUNDS = 15  # phase A warmup; phase B warms until the move lands


def run_phase(autotune: bool) -> dict:
    import numpy as np

    os.environ.update({
        "BYTEPS_VAN": "chaos:tcp",
        "BYTEPS_CHAOS_SEED": "11",
        # armed AFTER the fleet is up (target port unknown until then)
        "BYTEPS_CHAOS_DROP": "0",
        "BYTEPS_CHAOS_DELAY": "0",
        "BYTEPS_ELASTIC_RESHARD": "1",
        "BYTEPS_HEARTBEAT_INTERVAL": "0.1",
        "BYTEPS_FLIGHT_STEPS": "0",
        "BYTEPS_AUTOTUNE": "1" if autotune else "0",
        "BYTEPS_AUTOTUNE_INTERVAL_S": "0.2",
        "BYTEPS_AUTOTUNE_SWEEPS": "2",
        "BYTEPS_AUTOTUNE_FACTOR": "1.5",
        # one decisive action: a long cooldown keeps the measured window
        # on a settled placement instead of ping-ponging
        "BYTEPS_AUTOTUNE_COOLDOWN_S": "120",
        "BYTEPS_AUTOTUNE_MAX_MOVES": str(HOT_KEYS),
        "DMLC_PS_ROOT_URI": "127.0.0.1",
    })
    from byteps_tpu.common.config import Config
    from byteps_tpu.common.hashing import HashRing
    from byteps_tpu.common.types import DataType
    from byteps_tpu.comm.ps_client import PSClient
    from byteps_tpu.comm.rendezvous import Scheduler
    from byteps_tpu.core.telemetry import counters
    from byteps_tpu.server.server import PSServer

    f32 = int(DataType.FLOAT32)
    sched = Scheduler(num_workers=1, num_servers=2, host="127.0.0.1")
    sched.start()
    os.environ["DMLC_PS_ROOT_PORT"] = str(sched.port)
    cfg = Config(num_worker=1, num_server=2, elastic_reshard=True,
                 heartbeat_interval=0.1, rpc_retries=6, rpc_deadline_s=5.0,
                 ps_root_port=sched.port)
    fleet = [PSServer(Config(num_worker=1, num_server=2, elastic_reshard=True,
                             heartbeat_interval=0.1, ps_root_port=sched.port))
             for _ in range(2)]
    for s in fleet:
        threading.Thread(target=s.start, daemon=True).start()
    # the slow server = rank 0's port, read from the live registration
    # table (ranks assign as REGISTERs arrive)
    deadline = time.monotonic() + 10
    while True:
        with sched._lock:
            nodes = list(sched._nodes["server"])
        if len(nodes) >= 2:
            break
        if time.monotonic() > deadline:
            raise RuntimeError("servers never registered")
        time.sleep(0.05)
    victim_port = next(n.port for n in nodes if n.rank == 0)
    from byteps_tpu.comm.transport import Op

    os.environ["BYTEPS_CHAOS_TARGET_PORT"] = str(victim_port)
    os.environ["BYTEPS_CHAOS_OPS"] = f"{int(Op.PUSH)},{int(Op.PULL)}"
    os.environ["BYTEPS_CHAOS_DELAY"] = "1.0"
    os.environ["BYTEPS_CHAOS_DELAY_MS"] = str(DELAY_MS)

    pc = PSClient(cfg)
    pc.connect()
    ring = HashRing([0, 1], vnodes=cfg.ring_vnodes)
    hot = [k << 16 for k in range(4096) if ring.owner(k << 16) == 0][:HOT_KEYS]
    cold = [k << 16 for k in range(4096) if ring.owner(k << 16) == 1][:COLD_KEYS]
    keys = hot + cold
    assert len(hot) == HOT_KEYS and len(cold) == COLD_KEYS
    for k in keys:
        pc.init_tensor(k, DIM, f32)
    rng = np.random.default_rng(5)
    grads = {k: rng.standard_normal(DIM).astype(np.float32) for k in keys}

    def round_trip(ver: int) -> None:
        for k in keys:
            acked = threading.Event()
            pc.push(k, grads[k].tobytes(), f32, ver, lambda e=acked: e.set())
            assert acked.wait(30), f"push {k} hung"
        for k in keys:
            got = threading.Event()
            box: list = []
            pc.pull(k, ver, lambda p, b=box, e=got: (b.append(p), e.set()))
            assert got.wait(30), f"pull {k} hung"
            np.testing.assert_array_equal(
                np.frombuffer(box[0], np.float32), grads[k]
            )

    result = {"autotune": autotune}
    try:
        ver = 0
        # warmup: fixed rounds off; with the tuner on, warm until the
        # rebalance lands (bounded), then settle a couple of rounds
        if autotune:
            deadline = time.monotonic() + 45
            moved = False
            while time.monotonic() < deadline:
                ver += 1
                round_trip(ver)
                if counters().get("migration_keys_moved") > 0:
                    moved = True
                    break
            result["rebalanced"] = moved
            for _ in range(3):  # settle: drain chases/parked requests
                ver += 1
                round_trip(ver)
        else:
            for _ in range(WARMUP_ROUNDS):
                ver += 1
                round_trip(ver)
            result["rebalanced"] = False
        t0 = time.monotonic()
        for _ in range(MEASURE_ROUNDS):
            ver += 1
            round_trip(ver)
        dt = time.monotonic() - t0
        result.update({
            "rounds": MEASURE_ROUNDS,
            "seconds": round(dt, 4),
            "steps_per_s": round(MEASURE_ROUNDS / dt, 3),
            "migration_keys_moved": counters().get("migration_keys_moved"),
            "server_generation": pc.server_generation,  # 0 = no re-init
        })
        if autotune and sched.tuner is not None:
            result["tuner_actions"] = [
                {"rule": a["rule"], "evidence": a.get("evidence")}
                for a in sched.tuner.actions
            ]
            result["overrides"] = {
                str(k): r for k, r in sched.tuner.state.overrides.items()
            }
    finally:
        pc.close()
        for s in fleet:
            s.stop()
        sched.stop()
    return result


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--phase", choices=["off", "on"],
                    help="(internal) run one phase in THIS process")
    ap.add_argument("--out", default="AUTOTUNE_BENCH_r01.json")
    args = ap.parse_args(argv)
    if args.phase:
        out = run_phase(autotune=args.phase == "on")
        print("PHASE_RESULT " + json.dumps(out))
        return 0
    results = {}
    for phase in ("off", "on"):
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--phase", phase],
            capture_output=True, text=True, timeout=600,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        line = next(
            (ln for ln in proc.stdout.splitlines()
             if ln.startswith("PHASE_RESULT ")), None,
        )
        if proc.returncode != 0 or line is None:
            sys.stderr.write(proc.stdout[-4000:] + proc.stderr[-4000:])
            raise RuntimeError(f"phase {phase} failed")
        results[phase] = json.loads(line[len("PHASE_RESULT "):])
    ratio = results["on"]["steps_per_s"] / max(
        1e-9, results["off"]["steps_per_s"]
    )
    doc = {
        "bench": "autotune skewed-load A/B (hot_key_rebalance)",
        "schedule": {
            "hot_keys_on_slow_server": HOT_KEYS,
            "cold_keys": COLD_KEYS,
            "dim": DIM,
            "chaos_delay_ms_per_frame_on_rank0": DELAY_MS,
            "measure_rounds": MEASURE_ROUNDS,
        },
        "off": results["off"],
        "on": results["on"],
        "speedup_on_vs_off": round(ratio, 3),
        "notes": (
            "same seeded chaos schedule both phases; rank 0 owns "
            f"{HOT_KEYS}/{HOT_KEYS + COLD_KEYS} keys and every PUSH/PULL "
            "frame to it is delayed; with BYTEPS_AUTOTUNE=1 the hot-key "
            "rebalance moves the hot keys to rank 1 through the live "
            "migration plane (no re-init; bitwise pulls asserted every "
            "round including through the move)"
        ),
    }
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=2)
    print(json.dumps(doc, indent=2))
    if not results["on"].get("rebalanced"):
        print("WARNING: rebalance never fired in the ON phase",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
