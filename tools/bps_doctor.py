#!/usr/bin/env python
"""bps_doctor — rank diagnoses from a flight-recorder bundle or live endpoints.

The telemetry plane can already *show* everything (bps_top, trace_merge,
the Prometheus families); this tool makes it *conclude*.  It loads a
diagnostic bundle directory (written by a flight-recorder trigger —
``ledger.jsonl`` + ``metrics.json`` + ``trigger.json`` + ``config.json``,
docs/observability.md "Flight recorder & doctor") or scrapes live
Prometheus endpoints (``--live URL...``), runs a ranked rule table that
codifies the docs/troubleshooting.md field guide, and prints each
matching diagnosis with the evidence it matched, the doc anchor to read,
and the knob to turn.

Every rule names a real anchor in docs/troubleshooting.md, and every
field-guide failure mode names a rule (or carries an explicit
``no-rule:`` waiver) — ``tools/check_doctor_rules.py`` (tier-1) fails
the build when either direction rots.

Usage:

    python tools/bps_doctor.py ./flight_bundles/20260804-*-straggler_server-*
    python tools/bps_doctor.py --live http://w0:9102 http://sched:9102
    python tools/bps_doctor.py --json <bundle-dir>     # machine-readable

Stdlib only (the doctor must run on a box where byteps itself won't).
"""

from __future__ import annotations

import argparse
import json
import math
import os
import re
import statistics
import sys
import urllib.request
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

TROUBLESHOOTING = "docs/troubleshooting.md"


def slugify(heading: str) -> str:
    """Markdown heading → anchor slug.  Deliberately dumb (lowercase,
    non-alphanumeric runs → one '-') and SHARED with
    tools/check_doctor_rules.py so the two can never disagree."""
    return re.sub(r"[^a-z0-9]+", "-", heading.lower()).strip("-")


_LABEL_RE = re.compile(r'(\w+)="([^"]*)"')


def parse_labels(s: str) -> Dict[str, str]:
    """'{server="1",stage="PUSH"}' (or '') → dict."""
    return dict(_LABEL_RE.findall(s or ""))


class View:
    """One normalized read surface over a bundle OR a live scrape:
    flat counters, labeled counter slices, histogram summaries
    (count/p50/p90/p99 per label set), gauges, and the flight ledger
    (empty in live mode — the ledger lives node-side)."""

    def __init__(self) -> None:
        self.counters: Dict[str, float] = {}
        self.labeled: Dict[str, List[Tuple[Dict[str, str], float]]] = {}
        self.hists: Dict[str, List[Tuple[Dict[str, str], Dict[str, float]]]] = {}
        self.gauges: Dict[str, List[Tuple[Dict[str, str], float]]] = {}
        self.ledger: List[dict] = []
        self.trigger: Optional[dict] = None
        self.sources: List[str] = []

    # --- accessors rules use --------------------------------------------

    def counter(self, *names: str) -> float:
        return sum(self.counters.get(n, 0.0) for n in names)

    def labeled_by(self, name: str, label: str) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for labels, v in self.labeled.get(name, []):
            key = labels.get(label)
            if key is not None:
                out[key] = out.get(key, 0.0) + v
        return out

    def hist_by(self, name: str, label: str) -> Dict[str, Dict[str, float]]:
        """{label_value: summary} for series of ``name`` carrying
        ``label``; same-value series (several scrape sources) keep the
        max p99 and summed count."""
        out: Dict[str, Dict[str, float]] = {}
        for labels, summ in self.hists.get(name, []):
            key = labels.get(label)
            if key is None:
                continue
            cur = out.get(key)
            if cur is None:
                out[key] = dict(summ)
            else:
                cur["count"] = cur.get("count", 0) + summ.get("count", 0)
                for q in ("p50", "p90", "p99"):
                    cur[q] = max(cur.get(q, 0.0), summ.get(q, 0.0))
        return out

    def hist_top(self, name: str, q: str = "p99") -> float:
        """The worst quantile across every series of a family."""
        return max(
            (summ.get(q, 0.0) for _l, summ in self.hists.get(name, [])),
            default=0.0,
        )

    def hist_count(self, name: str) -> float:
        return sum(s.get("count", 0) for _l, s in self.hists.get(name, []))

    def gauge_max(self, name: str) -> float:
        return max((v for _l, v in self.gauges.get(name, [])), default=0.0)

    def ledger_triggers(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for r in self.ledger:
            for rule in r.get("trig") or ():
                out[rule] = out.get(rule, 0) + 1
        if self.trigger and self.trigger.get("rule"):
            out[self.trigger["rule"]] = out.get(self.trigger["rule"], 0) + 1
        return out

    # --- loaders ---------------------------------------------------------

    def load_bundle(self, path: str) -> "View":
        self.sources.append(path)
        mpath = os.path.join(path, "metrics.json")
        if os.path.exists(mpath):
            with open(mpath) as f:
                snap = json.load(f)
            for name, v in (snap.get("counters") or {}).items():
                self.counters[name] = self.counters.get(name, 0.0) + float(v)
            for name, per in (snap.get("counters_labeled") or {}).items():
                dst = self.labeled.setdefault(name, [])
                for lstr, v in per.items():
                    dst.append((parse_labels(lstr), float(v)))
            for series, summ in (snap.get("histograms") or {}).items():
                name, _, lstr = series.partition("{")
                self.hists.setdefault(name, []).append(
                    (parse_labels("{" + lstr if lstr else ""), dict(summ))
                )
            for series, v in (snap.get("gauges") or {}).items():
                name, _, lstr = series.partition("{")
                self.gauges.setdefault(name, []).append(
                    (parse_labels("{" + lstr if lstr else ""), float(v))
                )
        lpath = os.path.join(path, "ledger.jsonl")
        if os.path.exists(lpath):
            with open(lpath) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        try:
                            self.ledger.append(json.loads(line))
                        except ValueError:
                            continue
        tpath = os.path.join(path, "trigger.json")
        if os.path.exists(tpath):
            with open(tpath) as f:
                try:
                    self.trigger = json.load(f)
                except ValueError:
                    self.trigger = None
        return self

    def load_live(self, urls: List[str], timeout: float = 3.0) -> "View":
        for url in urls:
            if "://" not in url:
                url = "http://" + url
            self.sources.append(url)
            body = urllib.request.urlopen(url, timeout=timeout).read().decode()
            self._parse_prometheus(body)
        return self

    def _parse_prometheus(self, body: str) -> None:
        hist_parts: Dict[Tuple[str, str], Dict[str, float]] = {}
        for line in body.splitlines():
            if not line or line.startswith("#"):
                continue
            try:
                series, value = line.rsplit(" ", 1)
                v = float(value)
            except ValueError:
                continue
            name, _, lstr = series.partition("{")
            lstr = "{" + lstr if lstr else ""
            if name.startswith("byteps_"):
                name = name[len("byteps_"):]
            if name.endswith("_labeled_total"):
                base = name[: -len("_labeled_total")]
                self.labeled.setdefault(base, []).append((parse_labels(lstr), v))
            elif name.endswith("_total"):
                base = name[: -len("_total")]
                self.counters[base] = self.counters.get(base, 0.0) + v
            elif name.endswith(("_p50", "_p90", "_p99", "_count", "_sum")):
                base, _, part = name.rpartition("_")
                if part == "sum" or name.endswith("_bucket"):
                    continue
                hist_parts.setdefault((base, lstr), {})[
                    "count" if part == "count" else part
                ] = v
            elif not name.endswith("_bucket"):
                self.gauges.setdefault(name, []).append((parse_labels(lstr), v))
        for (base, lstr), summ in hist_parts.items():
            if "p50" in summ or "p99" in summ:
                self.hists.setdefault(base, []).append(
                    (parse_labels(lstr), summ)
                )


# --- the rule table --------------------------------------------------------


@dataclass
class Finding:
    rule: str
    score: float
    diagnosis: str
    evidence: List[str]
    anchor: str
    knob: str


@dataclass
class Rule:
    """One executable row of the troubleshooting field guide: the
    predicate reads the View, the anchor points at the doc section it
    codifies (a real heading slug — tier-1-enforced), the knob is the
    first thing to turn."""

    name: str
    anchor: str
    knob: str
    fn: Callable[[View], Optional[Tuple[float, str, List[str]]]] = field(
        repr=False, default=None
    )

    def run(self, view: View) -> Optional[Finding]:
        try:
            out = self.fn(view)
        except Exception:  # noqa: BLE001 — one broken rule ≠ no diagnosis
            return None
        if out is None:
            return None
        score, diagnosis, evidence = out
        return Finding(self.name, round(score, 1), diagnosis, evidence,
                       f"{TROUBLESHOOTING}#{self.anchor}", self.knob)


_SLOW_ANCHOR = slugify("A step is slow — which metric to read first")


def _r_straggler_server(v: View):
    """One server rank's latency/retry totals run away from its peers."""
    per = v.hist_by("rpc_round_trip_seconds", "server")
    cells = {r: s for r, s in per.items()
             if r != "?" and s.get("count", 0) > 0}
    # the per-step ledger view (survives even when cumulative histograms
    # have averaged the incident away): worst per-record skew
    led_rank, led_skew = None, 0.0
    for rec in v.ledger:
        rpc = rec.get("rpc") or {}
        vals = {r: (p.get("p99", 0.0) if isinstance(p, dict) else float(p))
                for r, p in rpc.items() if r != "?"}
        if len(vals) < 2:
            continue
        worst = max(vals, key=vals.get)
        others = [x for r, x in vals.items() if r != worst]
        skew = vals[worst] / max(statistics.median(others), 1e-6)
        if skew > led_skew:
            led_rank, led_skew = worst, skew
    evidence, rank, skew = [], None, 0.0
    if len(cells) >= 2:
        worst = max(cells, key=lambda r: cells[r].get("p99", 0.0))
        others = [cells[r].get("p99", 0.0) for r in cells if r != worst]
        med = statistics.median(others)
        hskew = cells[worst].get("p99", 0.0) / max(med, 1e-6)
        if hskew >= 3.0:
            rank, skew = worst, hskew
            evidence.append(
                f"rpc_round_trip_seconds p99: server {worst} = "
                f"{cells[worst].get('p99', 0.0):.4f}s vs peer median "
                f"{med:.4f}s ({hskew:.0f}x)"
            )
    if led_skew >= 3.0 and (rank is None or led_rank == rank
                            or led_skew > skew):
        rank = led_rank if rank is None else rank
        skew = max(skew, led_skew)
        evidence.append(
            f"flight ledger: per-step RPC p99 skew up to {led_skew:.0f}x "
            f"toward server {led_rank}"
        )
    retries = v.labeled_by("rpc_retry", "server")
    expiries = v.labeled_by("rpc_deadline_expired", "server")
    for fam, per_rank in (("rpc_retry", retries),
                          ("rpc_deadline_expired", expiries)):
        if rank is not None and per_rank.get(rank, 0) > 0:
            evidence.append(
                f"{fam}_labeled_total{{server={rank}}} = "
                f"{int(per_rank[rank])}"
            )
    trig = v.ledger_triggers().get("straggler_server", 0)
    if trig:
        evidence.append(f"straggler_server trigger fired {trig}x on-node")
    if rank is None and retries and sum(retries.values()) >= 3:
        worst = max(retries, key=retries.get)
        others = [x for r, x in retries.items() if r != worst] or [0]
        if retries[worst] >= 3 * max(statistics.median(others), 1):
            rank, skew = worst, retries[worst]
            evidence.append(
                f"retries skewed to server {worst}: {int(retries[worst])} "
                f"vs peer median {statistics.median(others):.0f}"
            )
    if rank is None:
        return None
    score = 60 + min(30.0, 10 * math.log10(max(skew, 1.0)))
    return (
        score,
        f"server rank {rank} is the straggler: its RPC latency/retries "
        "run far ahead of every peer — that server is slow, sick, or "
        "behind a bad link",
        evidence,
    )


def _r_slow_step(v: View):
    """Steps much slower than the rolling median (flight slow_step)."""
    trig = v.ledger_triggers().get("slow_step", 0)
    durs = [r["dur"] for r in v.ledger
            if r.get("k") == "step" and r.get("dur") is not None]
    ev = []
    ratio = 0.0
    if len(durs) >= 8:
        med = statistics.median(durs)
        worst = max(durs)
        ratio = worst / max(med, 1e-9)
        if ratio >= 3.0:
            ev.append(
                f"ledger: worst step {worst:.3f}s vs median {med:.3f}s "
                f"({ratio:.1f}x)"
            )
    if trig:
        ev.append(f"slow_step trigger fired {trig}x on-node")
    if not ev:
        return None
    return (
        38 + min(10.0, ratio),
        "individual steps are stalling far past the typical step time — "
        "read the per-stage and per-server rows below this one to name "
        "the hop",
        ev,
    )


def _r_wire_bottleneck(v: View):
    rpc = v.hist_top("rpc_round_trip_seconds")
    srv = max(v.hist_top("server_sum_seconds"),
              v.hist_top("native_server_sum_seconds"))
    if rpc <= 0 or srv <= 0:
        return None
    if rpc < 5 * srv or rpc < 0.005:
        return None
    return (
        34,
        "the wire (or client overhead), not the server, is eating the "
        f"round trip: RPC p99 {rpc:.4f}s vs server sum p99 {srv:.4f}s",
        [f"rpc_round_trip_seconds p99 = {rpc:.4f}s",
         f"server sum p99 = {srv:.4f}s"],
    )


def _r_stage_stall(v: View):
    per = v.hist_by("stage_dwell_seconds", "stage")
    hot = {s: d for s, d in per.items() if d.get("p99", 0.0) >= 1.0}
    trig = v.ledger_triggers().get("queue_stall", 0)
    if not hot and not trig:
        return None
    ev = [f"stage_dwell_seconds{{stage={s}}} p99 = {d['p99']:.2f}s"
          for s, d in sorted(hot.items(), key=lambda kv: -kv[1]["p99"])]
    if trig:
        ev.append(f"queue_stall trigger fired {trig}x on-node")
    worst = max(hot, key=lambda s: hot[s]["p99"]) if hot else "?"
    return (
        30 + min(10.0, max((d["p99"] for d in hot.values()), default=0.0)),
        f"pipeline stage {worst} is where tasks park — queue wait is "
        "inside the dwell, so this names the stalled stage directly",
        ev,
    )


def _r_server_stall(v: View):
    srv = max(v.hist_top("server_sum_seconds"),
              v.hist_top("native_server_sum_seconds"),
              v.hist_top("server_publish_seconds"),
              v.hist_top("native_server_publish_seconds"))
    rpc = v.hist_top("rpc_round_trip_seconds")
    if srv < 0.05 or (rpc > 0 and srv < 0.5 * rpc):
        return None
    return (
        33,
        "the server-side ledger/summation path is the bottleneck "
        f"(sum/publish p99 {srv:.4f}s)",
        [f"server sum/publish p99 = {srv:.4f}s",
         f"rpc_round_trip_seconds p99 = {rpc:.4f}s"],
    )


def _r_hot_stripe(v: View):
    per = v.hist_by("native_stripe_sum_seconds", "stripe")
    trig = v.ledger_triggers().get("hot_stripe", 0)
    ev = []
    if len(per) >= 2:
        counts = {s: d.get("count", 0) for s, d in per.items()}
        worst = max(counts, key=counts.get)
        others = [c for s, c in counts.items() if s != worst]
        med = statistics.median(others)
        if counts[worst] >= 3 * max(med, 1):
            ev.append(
                f"native_stripe_sum_seconds counts: stripe {worst} = "
                f"{int(counts[worst])} vs sibling median {med:.0f}"
            )
    if trig:
        ev.append(f"hot_stripe trigger fired {trig}x on-node")
    if not ev:
        return None
    return (
        32,
        "one native reducer stripe is doing most of the summation — the "
        "key hash is aliasing hot keys onto one reducer",
        ev,
    )


def _r_fusion_overhead(v: View):
    frames = v.counter("fused_frames")
    per = v.hists.get("fused_pack_keys", [])
    if not frames or not per:
        return None
    p50 = max(s.get("p50", 0.0) for _l, s in per)
    if p50 > 1.0:
        return None
    return (
        15,
        "fusion is pure overhead: packs carry one key at the median "
        "(nothing coalesces)",
        [f"fused_pack_keys p50 = {p50:.1f} over "
         f"{int(frames)} fused frames"],
    )


def _r_retry_burn(v: View):
    retries = v.counter("rpc_retry")
    backoffs = v.hist_count("retry_backoff_seconds")
    if retries < 3 and backoffs < 3:
        return None
    return (
        22 + min(8.0, math.log10(max(retries, 1.0)) * 4),
        "the job is spending wall time sitting out retry backoffs — find "
        "the failing peer (straggler row) before raising the budget",
        [f"rpc_retry_total = {int(retries)}",
         f"retry_backoff_seconds count = {int(backoffs)}"],
    )


def _r_replay_landing(v: View):
    dedup = v.counter("push_dedup", "native_push_dedup")
    if dedup <= 0:
        return None
    return (
        18,
        "replayed pushes are landing (lost acks) — sums are safe "
        "(exactly-once ledger) but latency is paying for re-sends; the "
        "deadline may be tighter than the server's p99",
        [f"push_dedup(+native) total = {int(dedup)}"],
    )


def _r_healed_in_place(v: View):
    attempts = v.counter("resync_attempt")
    giveups = v.counter("resync_giveup")
    if attempts <= 0:
        return None
    ev = [f"resync_attempt_total = {int(attempts)}",
          f"resync_replayed_rounds_total = "
          f"{int(v.counter('resync_replayed_rounds'))}"]
    if giveups > 0:
        ev.append(f"resync_giveup_total = {int(giveups)} — heals FAILING")
        return (
            45,
            "in-place heals are failing and the job fell back to re-init "
            "— check whether the peer is actually down (eviction's job, "
            "not resync's)",
            ev,
        )
    per = v.labeled_by("resync_attempt", "server")
    if per:
        worst = max(per, key=per.get)
        ev.append(f"heals target server {worst}")
    return (
        26,
        "a worker healed in place: retries to one server exhausted, the "
        "recovery plane resynced and replayed the journaled rounds",
        ev,
    )


def _r_control_plane_stuck(v: View):
    deg = v.gauge_max("control_plane_degraded")
    flips = v.ledger_triggers().get("degraded_flip", 0)
    if deg < 1 and not flips:
        return None
    ev = [f"control_plane_degraded = {int(deg)}"]
    rc, rj = v.counter("sched_reconnect"), v.counter("sched_rejoin")
    if rc:
        ev.append(f"sched_reconnect_total = {int(rc)}, "
                  f"sched_rejoin_total = {int(rj)}")
    if flips:
        ev.append(f"degraded_flip trigger fired {flips}x on-node")
    score = 55 if deg >= 1 else 35
    return (
        score,
        "the scheduler link is (or was) down: training continues on the "
        "last book, but resize/evict/aggregate are frozen until the "
        "reconnect machine rejoins",
        ev,
    )


def _r_zombie_scheduler(v: View):
    stale = v.counter("sched_stale_book")
    if stale <= 0:
        return None
    return (
        24,
        "a zombie scheduler (the pre-restart instance) is still sending "
        "books — harmless (incarnation-fenced) but kill the old process",
        [f"sched_stale_book_total = {int(stale)}"],
    )


def _r_compression_loss(v: View):
    off = v.counter("compression_auto_off")
    if off <= 0:
        return None
    return (
        14,
        "the adaptive compression policy disabled loss-making codecs — "
        "those keys' configured codec costs more wire than it saves",
        [f"compression_auto_off_total = {int(off)}"],
    )


def _r_chaos_active(v: View):
    total = v.counter("chaos_drop", "chaos_delay", "chaos_disconnect",
                      "chaos_truncate", "chaos_corrupt",
                      "chaos_payload_corrupt")
    if total <= 0:
        return None
    return (
        10,
        "the chaos van is armed and injected faults during this window — "
        "anomalies above may be rehearsed, not organic (injected faults "
        "are tagged `injected: true` on the merged timeline)",
        [f"chaos_* injected faults = {int(total)}"],
    )


_TENANT_ANCHOR = slugify("Multi-tenant: a job is starved or missing its SLO")


def _r_quota_starved(v: View):
    """One tenant's requests are mostly sitting in the admission meter:
    its offered load exceeds BYTEPS_JOB_QUOTA_MBPS, so the server defers
    (token bucket) a large share of them — the job sees its own quota,
    not the fleet, as the bottleneck."""
    deferred = v.labeled_by("job_quota_deferred", "job")
    served = v.labeled_by("server_job_requests", "job")
    worst, ratio = None, 0.0
    for job, d in deferred.items():
        tot = max(1.0, served.get(job, d))
        r = d / tot
        if d >= 10 and r > ratio:
            worst, ratio = job, r
    if worst is None or ratio < 0.2:
        return None
    quotas = {
        labels.get("job"): val
        for labels, val in v.gauges.get("server_job_quota_mbps", [])
    }
    evidence = [
        f"job_quota_deferred{{job={worst}}} = {deferred[worst]:.0f} "
        f"(~{100 * ratio:.0f}% of its {served.get(worst, 0):.0f} "
        "data-plane requests deferred by the admission meter)"
    ]
    if quotas.get(worst):
        evidence.append(
            f"server_job_quota_mbps{{job={worst}}} = {quotas[worst]:g} MB/s"
            " — the configured ceiling"
        )
    return (
        40 + min(40.0, 100 * ratio),
        f"job {worst} is quota-starved: its offered load exceeds its "
        "admission quota, so the server is deliberately delaying it "
        "(neighbors are protected; THIS job is rate-limited)",
        evidence,
    )


def _r_slo_breach(v: View):
    """A tenant's declared step-time SLO (BYTEPS_JOB_SLO_S) was blown —
    the flight recorder's slo_breach trigger fired."""
    fired = v.labeled_by("flight_trigger", "rule").get("slo_breach", 0.0)
    led = v.ledger_triggers().get("slo_breach", 0)
    n = max(fired, float(led))
    if n <= 0:
        return None
    evidence = [f"flight_trigger{{rule=slo_breach}} = {n:.0f}"]
    jobs = sorted({
        str(r.get("job")) for r in v.ledger
        if "slo_breach" in (r.get("trig") or ())
    })
    if jobs:
        evidence.append("breaching job(s): " + ", ".join(jobs))
    worst = max(
        (r.get("dur") or 0.0 for r in v.ledger
         if "slo_breach" in (r.get("trig") or ())),
        default=0.0,
    )
    if worst:
        evidence.append(f"worst breaching step: {worst:.3f}s")
    return (
        45 + min(30.0, 5 * n),
        "a tenant blew its step-time SLO (BYTEPS_JOB_SLO_S) — check "
        "whether a bulk neighbor saturates the shared fleet (give the "
        "latency job a higher BYTEPS_JOB_PRIORITY / quota the bulk job)",
        evidence,
    )


_CORRUPT_ANCHOR = slugify("Wire corruption: checksums are rejecting frames")


def _r_wire_corruption(v: View):
    """The end-to-end integrity plane (BYTEPS_WIRE_CHECKSUM) is
    rejecting frames: payload bits are flipping between the sender's
    CRC32C stamp and the receiver's verify — bad NIC/DRAM/link below
    TCP's 16-bit checksum.  Correctness is safe (rejected frames are
    dropped and retried through the exactly-once ledger); the evidence
    names where, and whether the faults are injected rehearsals."""
    fails = v.counter("wire_checksum_fail", "native_checksum_fail")
    if fails <= 0:
        return None
    ev = [f"wire_checksum_fail(+native) total = {int(fails)}"]
    per_srv = v.labeled_by("wire_checksum_fail", "server")
    if per_srv:
        worst = max(per_srv, key=per_srv.get)
        ev.append(
            f"worst path: server {worst} "
            f"({int(per_srv[worst])} rejected replies client-side)"
        )
    per_side = v.labeled_by("wire_checksum_fail", "side")
    if per_side:
        ev.append("by side: " + ", ".join(
            f"{s}={int(n)}" for s, n in sorted(per_side.items())
        ))
    drops = v.counter("wire_checksum_conn_drop", "native_checksum_conn_drop")
    if drops:
        ev.append(
            f"wire_checksum_conn_drop(+native) total = {int(drops)} — "
            "connections blew BYTEPS_CHECKSUM_CONN_LIMIT and were revived"
        )
    storms = v.ledger_triggers().get("corruption_storm", 0)
    if storms:
        ev.append(f"corruption_storm trigger fired {storms}x on-node")
    injected = v.counter("chaos_payload_corrupt")
    if injected:
        ev.append(
            f"chaos_payload_corrupt = {int(injected)} — (some of) these "
            "flips are injected rehearsals, not hardware"
        )
    score = 28 + min(30.0, math.log10(max(fails, 1.0)) * 10)
    if drops or storms:
        score += 15
    return (
        score,
        "payload bits are flipping on the wire and the checksum plane is "
        "catching them — sums stay bitwise-correct (drop + retry + "
        "exactly-once ledger) but every rejection costs a deadline; find "
        "the bad NIC/link before it gets worse",
        ev,
    )


_TUNER_ANCHOR = slugify("Autotuner: the control loop is acting up")


def _r_tuner_flapping(v: View):
    """The autotuner keeps taking actions its own canary reverts —
    oscillation: every flip costs a broadcast (and a migration wave for
    rebalances) without a lasting win."""
    acts = v.labeled_by("tune_action", "rule")
    rbs = v.labeled_by("tune_rollback", "rule")
    total_rb = sum(rbs.values())
    total_act = max(1.0, sum(acts.values()))
    if total_rb < 2:
        return None  # a single rollback is the guardrail WORKING
    ratio = total_rb / total_act
    if ratio < 0.4:
        return None
    worst = max(rbs, key=rbs.get)
    return (
        42 + min(20.0, 30 * ratio),
        f"the autotuner is flapping: {int(total_rb)} of "
        f"{int(total_act)} actions rolled back (worst rule: {worst}) — "
        "the workload is oscillating around a policy band; raise "
        "BYTEPS_AUTOTUNE_COOLDOWN_S / BYTEPS_AUTOTUNE_SWEEPS, or pin "
        "the knob and turn the tuner off for it",
        [f"tune_rollback total = {int(total_rb)} vs tune_action total = "
         f"{int(total_act)} ({100 * ratio:.0f}%)",
         f"tune_rollback{{rule={worst}}} = {int(rbs[worst])}"],
    )


def _r_rebalance_storm(v: View):
    """Hot-key rebalances firing back-to-back: placement is churning —
    every action is a live migration wave, and keys ping-ponging
    between servers means the load signal (or the workload) is less
    stable than the policy assumes."""
    moves = v.labeled_by("tune_action", "rule").get("hot_key_rebalance", 0)
    if moves < 3:
        return None
    migrated = v.counter("migration_keys_moved")
    ev = [f"tune_action{{rule=hot_key_rebalance}} = {int(moves)}"]
    if migrated:
        ev.append(f"migration_keys_moved_total = {int(migrated)} "
                  "(each rebalance is a live migration wave)")
    rb = v.labeled_by("tune_rollback", "rule").get("hot_key_rebalance", 0)
    if rb:
        ev.append(f"tune_rollback{{rule=hot_key_rebalance}} = {int(rb)}")
    return (
        38 + min(15.0, 3.0 * moves),
        f"rebalance storm: {int(moves)} hot-key rebalances in this "
        "window — placement is churning instead of settling; raise "
        "BYTEPS_AUTOTUNE_FACTOR / BYTEPS_AUTOTUNE_COOLDOWN_S (or check "
        "whether one tenant's traffic is genuinely bursty)",
        ev,
    )


RULES: List[Rule] = [
    Rule("straggler_server", _SLOW_ANCHOR,
         "BYTEPS_DEAD_NODE_TIMEOUT_S (evict it) / fix the sick server",
         _r_straggler_server),
    Rule("slow_step", _SLOW_ANCHOR,
         "BYTEPS_FLIGHT_SLOW_FACTOR (trigger sensitivity)", _r_slow_step),
    Rule("wire_bottleneck", _SLOW_ANCHOR,
         "BYTEPS_TCP_STREAMS / check shaping + DCN", _r_wire_bottleneck),
    Rule("stage_stall", _SLOW_ANCHOR,
         "per stage: BYTEPS_PARTITION_BYTES / BYTEPS_THREADPOOL_SIZE / "
         "BYTEPS_MIN_COMPRESS_BYTES", _r_stage_stall),
    Rule("server_stall", _SLOW_ANCHOR,
         "BYTEPS_SERVER_ENGINE_THREAD / BYTEPS_SERVER_NATIVE=1 / "
         "BYTEPS_KEY_HASH_FN=mixed", _r_server_stall),
    Rule("hot_stripe", _SLOW_ANCHOR,
         "BYTEPS_SERVER_STRIPES / BYTEPS_KEY_HASH_FN", _r_hot_stripe),
    Rule("fusion_overhead", _SLOW_ANCHOR,
         "BYTEPS_FUSION_CYCLE_MS up or BYTEPS_FUSION_THRESHOLD down",
         _r_fusion_overhead),
    Rule("retry_burn", _SLOW_ANCHOR,
         "fix the failing peer first; then BYTEPS_RPC_RETRIES",
         _r_retry_burn),
    Rule("replay_landing", _SLOW_ANCHOR,
         "BYTEPS_RPC_DEADLINE_S above the server's p99", _r_replay_landing),
    Rule("healed_in_place", _SLOW_ANCHOR,
         "BYTEPS_JOURNAL_ROUNDS / check the target server's health",
         _r_healed_in_place),
    Rule("control_plane_stuck", _SLOW_ANCHOR,
         "restart the scheduler on the SAME address; "
         "BYTEPS_SCHED_RECONNECT_RETRIES", _r_control_plane_stuck),
    Rule("zombie_scheduler", _SLOW_ANCHOR,
         "kill the superseded scheduler process", _r_zombie_scheduler),
    Rule("compression_loss", _SLOW_ANCHOR,
         "BYTEPS_COMPRESSION_AUTO_RATIO / pick a codec with a real win",
         _r_compression_loss),
    Rule("chaos_active", _SLOW_ANCHOR,
         "unset BYTEPS_CHAOS_* if this is not a rehearsal",
         _r_chaos_active),
    Rule("wire_corruption", _CORRUPT_ANCHOR,
         "replace the corrupting NIC/link; BYTEPS_CHECKSUM_CONN_LIMIT "
         "tunes the revival threshold", _r_wire_corruption),
    Rule("quota_starved", _TENANT_ANCHOR,
         "BYTEPS_JOB_QUOTA_MBPS up (or shed the job's offered load)",
         _r_quota_starved),
    Rule("slo_breach", _TENANT_ANCHOR,
         "BYTEPS_JOB_PRIORITY up for the latency job / "
         "BYTEPS_JOB_QUOTA_MBPS down for the bulk neighbor",
         _r_slo_breach),
    Rule("tuner_flapping", _TUNER_ANCHOR,
         "BYTEPS_AUTOTUNE_COOLDOWN_S / BYTEPS_AUTOTUNE_SWEEPS up, or pin "
         "the knob and disable the tuner", _r_tuner_flapping),
    Rule("rebalance_storm", _TUNER_ANCHOR,
         "BYTEPS_AUTOTUNE_FACTOR / BYTEPS_AUTOTUNE_COOLDOWN_S up",
         _r_rebalance_storm),
]


def diagnose(view: View) -> List[Finding]:
    """Run every rule; findings ranked most-severe first."""
    findings = [f for f in (r.run(view) for r in RULES) if f is not None]
    findings.sort(key=lambda f: -f.score)
    return findings


def render(findings: List[Finding], view: View) -> str:
    lines = [
        f"bps_doctor — {len(findings)} diagnosis(es) from "
        f"{', '.join(view.sources) or 'nothing'}"
    ]
    if not findings:
        lines.append("  nothing matched: no failure-mode signature in "
                     "this window (or the bundle is empty)")
    for i, f in enumerate(findings, 1):
        lines.append(f"{i:3d}. [{f.rule} {f.score:5.1f}] {f.diagnosis}")
        for ev in f.evidence:
            lines.append(f"       evidence: {ev}")
        lines.append(f"       read: {f.anchor}")
        lines.append(f"       knob: {f.knob}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("bundle", nargs="*",
                    help="flight-recorder bundle directory(ies)")
    ap.add_argument("--live", nargs="+", default=[],
                    help="scrape live Prometheus endpoints instead")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    args = ap.parse_args(argv)
    if not args.bundle and not args.live:
        ap.error("give a bundle directory or --live URLs")
    view = View()
    for b in args.bundle:
        if not os.path.isdir(b):
            print(f"not a bundle directory: {b}", file=sys.stderr)
            return 2
        view.load_bundle(b)
    if args.live:
        view.load_live(args.live)
    findings = diagnose(view)
    if args.json:
        print(json.dumps(
            [f.__dict__ for f in findings], indent=2, default=str
        ))
    else:
        print(render(findings, view))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
