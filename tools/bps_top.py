#!/usr/bin/env python
"""bps_top — live terminal view of the byteps metrics plane.

Polls one or more Prometheus exposition endpoints (``BYTEPS_METRICS_PORT``
per process, or the scheduler's cluster aggregate) and renders the
signals docs/observability.md says to read first: RPC round-trip
percentiles, per-stage dwell, retry/dedupe/chaos counters (with per-server
breakdown when present), fusion pack quality, server sum/publish latency,
and push/pull throughput.  Counter RATES are computed between polls.

Usage:

    python tools/bps_top.py http://127.0.0.1:9102            # one endpoint
    python tools/bps_top.py http://w0:9102 http://sched:9102 # several
    python tools/bps_top.py --once http://127.0.0.1:9102     # single frame

No dependencies beyond the stdlib; parses the text exposition directly.
"""

from __future__ import annotations

import argparse
import re
import sys
import time
import urllib.request
from typing import Dict, Tuple

Sample = Dict[Tuple[str, str], float]  # (metric, label-string) → value


def scrape(url: str, timeout: float = 2.0) -> Sample:
    if "://" not in url:
        url = "http://" + url
    body = urllib.request.urlopen(url, timeout=timeout).read().decode()
    out: Sample = {}
    for line in body.splitlines():
        if not line or line.startswith("#"):
            continue
        try:
            series, value = line.rsplit(" ", 1)
            name, _, labels = series.partition("{")
            out[(name, "{" + labels if labels else "")] = float(value)
        except ValueError:
            continue
    return out


def _fmt_s(v: float) -> str:
    if v >= 1.0:
        return f"{v:7.2f}s "
    if v >= 1e-3:
        return f"{v * 1e3:7.2f}ms"
    return f"{v * 1e6:7.1f}µs"


def _histo_rows(s: Sample) -> list:
    """Latency rows with percentiles.  Native-engine families
    (``byteps_native_*``, fed through the histogram-provider seam) sort
    NEXT TO their Python twins — ``native_rpc_round_trip_seconds``
    lands beside ``rpc_round_trip_seconds`` tagged ``[native]`` — so a
    mixed-engine cluster reads in one screen."""
    rows = []
    fams = sorted(
        {n[: -len("_p50")] for (n, _lbl) in s if n.endswith("_p50")},
        # group by the engine-stripped name, python row first
        key=lambda f: (f.replace("byteps_native_", "byteps_"),
                       "native_" in f),
    )
    for fam in fams:
        disp = fam.replace("byteps_", "")
        if disp.startswith("native_"):
            disp = disp[len("native_"):] + " [native]"
        for lbl in sorted({l for (n, l) in s if n == fam + "_p50"}):
            count = s.get((fam + "_count", lbl), 0)
            rows.append((
                disp + (lbl or ""),
                int(count),
                s.get((fam + "_p50", lbl), 0.0),
                s.get((fam + "_p90", lbl), 0.0),
                s.get((fam + "_p99", lbl), 0.0),
            ))
    return rows


_SPARK = "▁▂▃▄▅▆▇█"


def _sparkline(vals: list) -> str:
    """Tiny unicode sparkline, scaled to the row's own max."""
    if not vals:
        return ""
    top = max(vals) or 1.0
    return "".join(
        _SPARK[min(len(_SPARK) - 1, int(v / top * (len(_SPARK) - 1)))]
        for v in vals
    )


def render(url: str, cur: Sample, prev: Sample, dt: float,
           hist: dict = None) -> str:
    lines = [f"── {url} " + "─" * max(0, 60 - len(url))]
    # gauges
    for (name, lbl), v in sorted(cur.items()):
        if name == "byteps_pushpull_mbps":
            lines.append(f"  push/pull throughput : {v:10.2f} MB/s")
    # flight-recorder steps row (docs/observability.md "Flight recorder
    # & doctor"): last-N step-time sparkline per node from the
    # node_step_seconds gauge history across polls, the scheduler-marked
    # straggler rank starred, and flight trigger counts per rule.  On a
    # node endpoint the gauge is unlabeled; on the scheduler aggregate
    # each node's series carries {role, rank}.
    straggler = cur.get(("byteps_cluster_straggler_rank", ""), -1.0)
    step_rows = []
    for (name, lbl), v in cur.items():
        if name != "byteps_node_step_seconds":
            continue
        series = None
        if hist is not None:
            series = hist.setdefault((name, lbl), [])
            series.append(v)
            del series[:-24]
        rm = re.search(r'rank="(-?\d+)"', lbl)
        role_m = re.search(r'role="([^"]*)"', lbl)
        who = (
            f"{role_m.group(1) if role_m else 'node'}"
            f"{rm.group(1) if rm else ''}"
        )
        star = (
            "*" if rm and (role_m is None or role_m.group(1) == "worker")
            and float(rm.group(1)) == straggler else " "
        )
        step_rows.append((who, star, v, list(series or [v])))
    if step_rows:
        lines.append(f"  {'steps (sparkline = last polls)':42s} {'last':>9s}")
        for who, star, v, series in sorted(step_rows):
            lines.append(
                f"  {who + star:10s} {_sparkline(series):24s}"
                f" {_fmt_s(v):>12s}"
            )
        trig = {}
        for (name, lbl), v in cur.items():
            if name == "byteps_flight_trigger_labeled_total":
                tm = re.search(r'rule="([^"]*)"', lbl)
                if tm:
                    trig[tm.group(1)] = trig.get(tm.group(1), 0) + int(v)
        if trig:
            cells = " ".join(f"{r}={n}" for r, n in sorted(trig.items()))
            lines.append(f"  flight triggers      : {cells}")
    # per-tenant row (docs/async.md): one line per JOB sharing the fleet
    # — last-N step-time sparkline (job_step_last_seconds gauge history
    # across polls) plus quota utilization, the delta rate of the job's
    # served bytes against its configured server_job_quota_mbps ceiling.
    tenant_rows = {}
    for (name, lbl), v in cur.items():
        if name != "byteps_job_step_last_seconds":
            continue
        jm = re.search(r'job="([^"]*)"', lbl)
        if not jm:
            continue
        series = None
        if hist is not None:
            series = hist.setdefault((name, lbl), [])
            series.append(v)
            del series[:-24]
        row = tenant_rows.setdefault(
            jm.group(1), {"last": 0.0, "series": [], "util": None}
        )
        row["last"] = max(row["last"], v)
        row["series"] = list(series or [v])
    quotas, rates = {}, {}
    for (name, lbl), v in cur.items():
        jm = re.search(r'job="([^"]*)"', lbl)
        if not jm:
            continue
        if name == "byteps_server_job_quota_mbps":
            # quotas are enforced PER SERVER (ROADMAP note), and the
            # aggregate carries one series per server rank — the fleet
            # ceiling the summed byte rate compares against is the SUM
            quotas[jm.group(1)] = quotas.get(jm.group(1), 0.0) + v
        elif name == "byteps_server_job_bytes_labeled_total" and dt > 0:
            d = v - prev.get((name, lbl), 0.0)
            rates[jm.group(1)] = rates.get(jm.group(1), 0.0) + max(0.0, d) / dt
    for job, mbps in quotas.items():
        row = tenant_rows.setdefault(
            job, {"last": 0.0, "series": [], "util": None}
        )
        rate = rates.get(job, 0.0) / 1e6  # bytes/s → MB/s
        row["util"] = (rate, mbps)
    if tenant_rows:
        lines.append(
            f"  {'tenants (job: steps | quota use)':42s} {'last':>9s}"
        )
        for job in sorted(tenant_rows, key=lambda j: int(j) if j.isdigit() else 0):
            row = tenant_rows[job]
            cell = f"  job {job:<6s} {_sparkline(row['series']):24s}"
            if row["last"]:
                cell += f" {_fmt_s(row['last']):>12s}"
            if row["util"] is not None:
                rate, mbps = row["util"]
                pct = 100.0 * rate / mbps if mbps > 0 else 0.0
                cell += f"  quota {rate:6.2f}/{mbps:g} MB/s ({pct:3.0f}%)"
            lines.append(cell)
    # reducer backlog of the key-striped native engine, one cell per
    # stripe — a persistently deep cell while its siblings sit at 0 is
    # the hot-stripe signature (docs/perf.md).  Sorted numerically (s2
    # before s10); the series also carry a `server` instance label, so
    # cells are prefixed with it when more than one server shares the
    # endpoint (scaling_bench threads mode).
    depths = []
    for (name, lbl), v in cur.items():
        if name != "byteps_native_stripe_queue_depth":
            continue
        sm = re.search(r'stripe="(\d+)"', lbl)
        srv = re.search(r'server="([^"]*)"', lbl)
        depths.append((srv.group(1) if srv else "",
                       int(sm.group(1)) if sm else -1, v))
    if depths:
        many = len({s for s, _, _ in depths}) > 1
        cells = " ".join(
            (f"{srv}:" if many else "") + f"s{i}={int(v)}"
            for srv, i, v in sorted(depths)
        )
        lines.append(f"  stripe queue depth   : {cells}")
    # control plane (docs/robustness.md "Control-plane recovery"): the
    # scheduler incarnation the aggregate belongs to, how many expected
    # nodes have not yet re-registered with it (nonzero only during a
    # rebirth's rejoin window), and how many nodes report themselves in
    # control_plane_degraded mode (scheduler link down, data plane
    # still training on the last book)
    inc = rejoining = None
    degraded = 0
    for (name, lbl), v in cur.items():
        if name == "byteps_cluster_sched_incarnation":
            inc = int(v)
        elif name == "byteps_cluster_rejoining_nodes":
            rejoining = int(v)
        elif name == "byteps_control_plane_degraded" and v:
            degraded += 1
    if inc is not None or rejoining or degraded:
        lines.append(
            "  control plane        : "
            + (f"incarnation {inc}" if inc is not None else "incarnation ?")
            + f" | rejoining {rejoining or 0} | degraded {degraded}"
        )
    # elastic resharding ownership (docs/robustness.md "migration flow"):
    # the scheduler aggregate carries the cluster map epoch plus each
    # server's heartbeat-shipped owned-key count and adopted epoch, so a
    # migration is watchable as keys draining from one rank's cell into
    # another's; a rank still on an older epoch is marked with '*'.
    map_epoch = None
    owned: Dict[int, float] = {}
    srv_epoch: Dict[int, float] = {}
    for (name, lbl), v in cur.items():
        if name == "byteps_cluster_map_epoch":
            map_epoch = int(v)
        elif name in ("byteps_server_owned_keys", "byteps_server_map_epoch"):
            rm = re.search(r'rank="(-?\d+)"', lbl)
            if rm is None:
                continue
            dst = owned if name.endswith("owned_keys") else srv_epoch
            dst[int(rm.group(1))] = v
    if map_epoch is not None or owned:
        cells = " ".join(
            f"r{r}={int(v)}"
            + ("*" if map_epoch is not None
               and srv_epoch.get(r, map_epoch) < map_epoch else "")
            for r, v in sorted(owned.items())
        )
        head = f"epoch {map_epoch}" if map_epoch is not None else "epoch ?"
        lines.append(
            f"  ownership map        : {head}"
            + (f" | owned keys {cells}" if cells else "")
        )
    # adaptive control plane (docs/autotune.md): the tuning epoch the
    # fleet runs under, per-rule action/rollback totals, and how many
    # keys the fleet codec consensus turned off on this node.  Only the
    # scheduler aggregate carries the epoch + tune counters; a node
    # endpoint may still show its tune_codec_off slice.
    tune_epoch = None
    tune_acts: Dict[str, int] = {}
    tune_rbs: Dict[str, int] = {}
    codec_off_keys = 0
    for (name, lbl), v in cur.items():
        if name == "byteps_cluster_tuning_epoch":
            tune_epoch = int(v)
        elif name == "byteps_tune_action_labeled_total":
            rm = re.search(r'rule="([^"]*)"', lbl)
            if rm:
                tune_acts[rm.group(1)] = tune_acts.get(rm.group(1), 0) + int(v)
        elif name == "byteps_tune_rollback_labeled_total":
            rm = re.search(r'rule="([^"]*)"', lbl)
            if rm:
                tune_rbs[rm.group(1)] = tune_rbs.get(rm.group(1), 0) + int(v)
        elif name == "byteps_tune_codec_off_total":
            codec_off_keys += int(v)
    if tune_epoch is not None or tune_acts or tune_rbs:
        cells = " ".join(
            f"{r}={n}" for r, n in sorted(tune_acts.items())
        ) or "none"
        rb_total = sum(tune_rbs.values())
        line = (
            "  autotune             : "
            + (f"epoch {tune_epoch}" if tune_epoch is not None else "epoch ?")
            + f" | actions {cells} | rollbacks {rb_total}"
        )
        if codec_off_keys:
            line += f" | fleet codec-off keys {codec_off_keys}"
        lines.append(line)
    # compressed wire path (docs/gradient-compression.md): cumulative
    # wire bytes the codecs removed vs shipped, and how many keys the
    # adaptive policy (BYTEPS_COMPRESSION_AUTO) turned OFF because their
    # observed ratio made compression a loss
    saved = tx = auto_off = 0
    for (name, lbl), v in cur.items():
        if lbl:
            continue  # flat totals only (labeled twins double-count)
        if name == "byteps_wire_bytes_saved_total":
            saved = int(v)
        elif name == "byteps_wire_tx_bytes_total":
            tx = int(v)
        elif name == "byteps_compression_auto_off_total":
            auto_off = int(v)
    if saved or auto_off:
        pct = 100.0 * saved / max(1, saved + tx)
        lines.append(
            f"  compression          : saved {saved / 1e6:.1f} MB on wire"
            f" ({pct:.0f}% of push bytes) | auto-disabled keys {auto_off}"
        )
    # latency families
    rows = _histo_rows(cur)
    if rows:
        lines.append(f"  {'latency':42s} {'count':>8s} {'p50':>9s} {'p90':>9s} {'p99':>9s}")
        for fam, count, p50, p90, p99 in rows:
            lines.append(
                f"  {fam:42s} {count:8d} {_fmt_s(p50)} {_fmt_s(p90)} {_fmt_s(p99)}"
            )
    # counters + rates (totals only; labeled series shown when nonzero)
    counter_rows = []
    for (name, lbl), v in sorted(cur.items()):
        if not name.endswith("_total"):
            continue
        rate = ""
        if dt > 0 and (name, lbl) in prev:
            r = (v - prev[(name, lbl)]) / dt
            if r:
                rate = f"{r:9.1f}/s"
        if v or rate:
            counter_rows.append(
                f"  {name.replace('byteps_', '')[: -len('_total')] + (lbl or ''):42s}"
                f" {int(v):10d} {rate}"
            )
    if counter_rows:
        lines.append(f"  {'counter':42s} {'total':>10s}   rate")
        lines.extend(counter_rows)
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("urls", nargs="+", help="metrics endpoints to poll")
    ap.add_argument("-i", "--interval", type=float, default=2.0)
    ap.add_argument("--once", action="store_true",
                    help="print one frame and exit (no screen clearing)")
    args = ap.parse_args(argv)
    prev: Dict[str, Sample] = {}
    hist: Dict[str, dict] = {}
    t_prev = time.monotonic()
    while True:
        frames = []
        now = time.monotonic()
        dt = now - t_prev
        for url in args.urls:
            try:
                cur = scrape(url)
            except Exception as e:  # noqa: BLE001 — a dead peer is a display fact
                frames.append(f"── {url}\n  unreachable: {e}")
                continue
            frames.append(render(
                url, cur, prev.get(url, {}), dt,
                hist=hist.setdefault(url, {}),
            ))
            prev[url] = cur
        t_prev = now
        out = "\n\n".join(frames)
        if args.once:
            print(out)
            return 0
        sys.stdout.write("\x1b[2J\x1b[H")  # clear + home
        print(f"bps_top — {time.strftime('%H:%M:%S')} "
              f"(every {args.interval:g}s, ctrl-c to quit)\n")
        print(out)
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    raise SystemExit(main())
