"""Seeded chaos soak: SGD under a random fault schedule must still learn.

Complement to tools/soak.py (which composes features): this tool
composes FAILURES.  A 1-worker/``--servers`` cluster runs N steps of
plain SGD on a quadratic bowl (loss = ||w||², gradient aggregated
through the PS data plane) while the chaos van (comm/chaos.py) injects
drops, delays, disconnects, truncated frames, and corrupted frames per
the seeded schedule — optionally hard-killing one server mid-run
(``--crash-at``) so the scheduler's liveness policy has to evict it and
the worker has to fail over.

Invariants checked every step and at exit:

- no hang: the whole run sits under a watchdog (``--timeout``);
- exactly-once summation: with 1 worker the aggregated gradient must be
  BITWISE equal to the pushed one — a double-summed replayed push or a
  lost contribution shows up immediately;
- the model learns: final loss < initial loss (the degraded steps were
  retried, not silently skipped);
- when chaos probabilities are nonzero, at least one fault was injected
  and at least one retry observed (the schedule really ran).

    python tools/chaos_soak.py --steps 60 --seed 7 --drop 0.05 --crash-at 20

Exit 0 = survived with all invariants held; any exception/timeout is a
reproducible failure (the seed is printed).  CI keeps the deterministic
fast path alive via tests/test_chaos.py's cluster schedule.
"""

from __future__ import annotations

import argparse
import os
import sys
import threading

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=2")

import numpy as np


def run_soak(
    steps: int = 60,
    seed: int = 7,
    servers: int = 2,
    drop: float = 0.05,
    delay: float = 0.05,
    disconnect: float = 0.0,
    truncate: float = 0.0,
    corrupt: float = 0.0,
    crash_at: int = -1,
    dim: int = 1024,
    one_sided: bool = False,
    reshard: bool = False,
    sched_crash: int = -1,
    autotune: bool = False,
    payload_corrupt: bool = False,
    checksums: bool = True,
    engine: str = "python",
) -> dict:
    """Run the soak in-process; returns a result dict (raises on any
    invariant violation).  Env mutations are process-wide — run via the
    CLI (fresh process) unless the caller owns the environment.

    ``one_sided``: instead of spraying faults everywhere, target seeded
    drops at the single connection between the worker and the server
    that owns the soak tensor's key (BYTEPS_CHAOS_TARGET_PORT, plus
    BYTEPS_CHAOS_OPS set to the PUSH/PULL op codes), with a retry
    budget small enough to
    exhaust — so the run exercises the in-place heal end-to-end: give-up
    → Op.RESYNC_QUERY → journal replay → rejoin, no re-init barrier
    (docs/robustness.md "healing flow").  Asserts the heal actually
    fired (``resync_attempt`` > 0).

    ``sched_crash``: hard-kill the SCHEDULER at that step (every control
    fd closes with no goodbye — the in-process SIGKILL equivalent) and
    restart it on the same address.  The run then asserts the
    control-plane recovery contract (docs/robustness.md): training
    stepped bitwise-correctly through the outage, every node
    re-registered with the reborn incarnation within the rejoin window
    with ZERO spurious evictions, the new incarnation's map epoch fences
    above the old one, and — composed with ``reshard`` — a subsequent
    live scale-up still works against the reborn scheduler.

    ``payload_corrupt`` (the ``--corrupt`` mode; docs/robustness.md
    "Wire integrity"): seeded single-bit payload flips at p≈0.05 on
    PUSH/PULL/FUSED frames (plus MIGRATE_STATE when composed with
    ``reshard``, so a corrupted authoritative-ledger shipment is
    exercised too), with ``BYTEPS_WIRE_CHECKSUM=1`` and fusion armed so
    fused frames actually flow.  Asserts bitwise pulls every step,
    ``wire_checksum_fail`` > 0 (the schedule really flipped bits and
    every flip was caught), and ``rpc_giveup`` == 0 (drops healed inside
    the retry budget).  ``checksums=False`` runs the SAME seeded flip
    schedule with the integrity plane off — the run is then EXPECTED to
    fail its bitwise assert (silent corruption); the ``--ab`` CLI flag
    automates that two-leg proof in subprocesses.

    ``engine``: ``python`` (default) or ``native`` — the C++ engine
    verifies ahead of its stripe rings and stamps replies through the
    same shared wire.h CRC32C (native servers preclude ``reshard``/
    ``one_sided``/``autotune`` composition, which need Python-engine
    state export)."""
    if one_sided and servers < 2:
        raise ValueError("--one-sided needs --servers >= 2 (one victim, "
                         "one healthy control)")
    if reshard and servers < 2:
        raise ValueError("--reshard needs --servers >= 2 (keys must have "
                         "somewhere to migrate)")
    if sched_crash >= 0 and reshard and sched_crash >= max(1, steps // 3):
        raise ValueError("--sched-crash must land before the --reshard "
                         "scale-up step (steps//3) so the resize runs "
                         "against the REBORN scheduler")
    if engine == "native" and (reshard or one_sided or autotune):
        raise ValueError("--engine native cannot compose with --reshard/"
                         "--one-sided/--autotune (Python-engine-only "
                         "state export; docs/robustness.md parity matrix)")
    corrupt_ops = ""
    if payload_corrupt:
        from byteps_tpu.comm.transport import Op as _Op

        ops = [int(_Op.PUSH), int(_Op.PULL), int(_Op.FUSED)]
        if reshard:
            ops.append(int(_Op.MIGRATE_STATE))
        corrupt_ops = ",".join(str(o) for o in ops)
        # a flips-only schedule: the mode asserts rpc_giveup == 0, which
        # only the integrity plane's drop-and-retry can guarantee — a
        # stray disconnect/truncate landing inside a retry burst could
        # exhaust a budget for reasons unrelated to corruption
        drop = delay = disconnect = truncate = corrupt = 0.0
    os.environ.update(
        {
            "BYTEPS_VAN": "chaos:tcp",
            "BYTEPS_CHAOS_SEED": str(seed),
            # one-sided mode arms the fault env only AFTER the fleet is
            # up, so server-side response lanes snapshot zero params and
            # the faults stay on the one worker→victim request lane
            "BYTEPS_CHAOS_DROP": "0" if one_sided else str(drop),
            "BYTEPS_CHAOS_DELAY": "0" if one_sided else str(delay),
            "BYTEPS_CHAOS_DELAY_MS": "10",
            "BYTEPS_CHAOS_DISCONNECT": "0" if one_sided else str(disconnect),
            "BYTEPS_CHAOS_TRUNCATE": "0" if one_sided else str(truncate),
            "BYTEPS_CHAOS_CORRUPT": "0" if one_sided else str(corrupt),
            # --corrupt mode (docs/robustness.md "Wire integrity"):
            # seeded payload bit-flips on the data-plane ops, checksums
            # on (unless the A/B control leg turned them off), fusion
            # armed so FUSED frames are in the blast radius
            "BYTEPS_CHAOS_PAYLOAD_CORRUPT":
                "0.05" if payload_corrupt else "0",
            "BYTEPS_CHAOS_OPS": corrupt_ops,
            "BYTEPS_WIRE_CHECKSUM":
                "1" if (payload_corrupt and checksums) else "0",
            "BYTEPS_FUSION_THRESHOLD": "65536" if payload_corrupt else "0",
            "BYTEPS_SERVER_NATIVE": "1" if engine == "native" else "0",
            "BYTEPS_RPC_DEADLINE_S": "0.3",
            "BYTEPS_INIT_DEADLINE_S": "0.5",
            # a small budget in one-sided mode so give-ups (and thus the
            # heal path) actually happen instead of retries absorbing all
            "BYTEPS_RPC_RETRIES": "2" if one_sided else "6",
            "BYTEPS_RPC_BACKOFF_S": "0.05",
            "BYTEPS_CONNECT_RETRY_S": "0.2",
            "BYTEPS_DEGRADED_STEP_RETRIES": "8",
            "BYTEPS_HEARTBEAT_INTERVAL": "0.1",
            "BYTEPS_DEAD_NODE_TIMEOUT_S": "0.8",
            # control-plane recovery (docs/robustness.md): survive the
            # --sched-crash outage and rejoin the reborn incarnation fast
            "BYTEPS_SCHED_RECONNECT_RETRIES": "80",
            "BYTEPS_SCHED_RECONNECT_BACKOFF_S": "0.05",
            "BYTEPS_SCHED_REJOIN_WINDOW_S": "10",
            "BYTEPS_FORCE_DISTRIBUTED": "1",
            # live migration instead of re-init barriers on server-set
            # changes (docs/robustness.md "migration flow")
            "BYTEPS_ELASTIC_RESHARD": "1" if reshard else "0",
            # adaptive control plane (docs/autotune.md): the soak's
            # invariants (bitwise pulls, exactly-once sums, no re-init)
            # must hold WHILE the tuner sweeps and possibly rebalances
            # under the same seeded faults — fast knobs so sweeps and
            # any hot-key action land inside the run
            "BYTEPS_AUTOTUNE": "1" if autotune else "0",
            "BYTEPS_AUTOTUNE_INTERVAL_S": "0.2",
            "BYTEPS_AUTOTUNE_SWEEPS": "2",
            "BYTEPS_AUTOTUNE_FACTOR": "1.5",
            "BYTEPS_AUTOTUNE_COOLDOWN_S": "2",
            "DMLC_NUM_WORKER": "1",
            "DMLC_NUM_SERVER": str(servers),
            "DMLC_PS_ROOT_URI": "127.0.0.1",
        }
    )

    from byteps_tpu.common.config import Config
    from byteps_tpu.comm.rendezvous import Scheduler
    from byteps_tpu.core.telemetry import counters
    from byteps_tpu.server.server import NativePSServer, PSServer

    server_cls = NativePSServer if engine == "native" else PSServer
    counters().reset()
    sched = Scheduler(num_workers=1, num_servers=servers, host="127.0.0.1")
    sched.start()
    os.environ["DMLC_PS_ROOT_PORT"] = str(sched.port)
    fleet = [server_cls(Config.from_env()) for _ in range(servers)]
    for srv in fleet:
        threading.Thread(target=srv.start, daemon=True).start()

    if one_sided:
        import time as _time

        from byteps_tpu.common.hashing import assign_server
        from byteps_tpu.comm.chaos import reset_fault_budget
        from byteps_tpu.comm.transport import Op

        # aim at the server that OWNS the soak tensor's key (declared
        # first ⇒ key 0) — faults on the other server's port would never
        # touch the data path.  Ranks are assigned as REGISTERs arrive,
        # but the address book (which sets fleet[i].rank) only ships once
        # the WORKER also registers — so read the scheduler's live
        # registration table directly.
        deadline = _time.monotonic() + 10
        while True:
            with sched._lock:
                nodes = list(sched._nodes["server"])
            if len(nodes) >= servers:
                break
            if _time.monotonic() > deadline:
                raise RuntimeError("servers never registered")
            _time.sleep(0.05)
        cfg0 = Config.from_env()
        owner_rank = assign_server(
            0, servers, fn=cfg0.key_hash_fn, coef=cfg0.built_in_hash_coef,
            mixed_mode=cfg0.enable_mixed_mode,
            mixed_bound=cfg0.mixed_mode_bound, num_workers=1,
        )
        victim_port = next(n.port for n in nodes if n.rank == owner_rank)
        os.environ["BYTEPS_CHAOS_TARGET_PORT"] = str(victim_port)
        os.environ["BYTEPS_CHAOS_OPS"] = f"{int(Op.PUSH)},{int(Op.PULL)}"
        os.environ["BYTEPS_CHAOS_DROP"] = str(max(drop, 0.4))
        reset_fault_budget()  # re-read BYTEPS_CHAOS_FAULT_BUDGET lazily

    import time as _time

    import byteps_tpu as bps

    rng = np.random.default_rng(seed)
    # --reshard trains several NAMED shards so the consistent-hash ring
    # re-homes a real subset of keys on every server-set change (one
    # tensor = one key could land on an unmoved ring segment).
    # --corrupt (without reshard) trains one small tensor (rides the
    # fuser) and one above-threshold tensor (plain PUSH/PULL), so the
    # flip schedule hits all three targeted frame shapes.
    n_shards = 8 if reshard else (2 if payload_corrupt else 1)
    sdim = max(4, dim // n_shards)
    sizes = [sdim] * n_shards
    if payload_corrupt and not reshard:
        sizes = [sdim, 24576]  # 96 KB > the 64 KB fusion threshold
    ws = [rng.standard_normal(s).astype(np.float32) for s in sizes]
    loss0 = float(sum(w @ w for w in ws))
    lr = 0.05
    up_at, down_at = max(1, steps // 3), max(2, (2 * steps) // 3)
    extra = None
    drained_ok = True
    sched_reborn = False
    try:
        bps.init()
        client = None
        if reshard:
            from byteps_tpu.core.state import get_state

            client = get_state().engine.client
        for step in range(steps):
            for i in range(n_shards):
                grad = 2.0 * ws[i]  # d/dw ||w||²
                agg = np.asarray(
                    bps.push_pull(grad, name=f"chaos_soak.w{i}", average=True)
                )
                # 1 worker ⇒ the averaged sum IS the gradient, bitwise; a
                # double-summed replay or dropped contribution breaks this
                np.testing.assert_array_equal(agg, grad)
                ws[i] = ws[i] - lr * agg
            if step == crash_at and servers > 1:
                fleet[-1].stop()  # involuntary: eviction must heal it
            if step == sched_crash:
                # hard-kill the SCHEDULER (in-process SIGKILL: every
                # control fd closes with no goodbye frame) and restart
                # it on the same address — nodes must ride through in
                # control_plane_degraded mode and rejoin the new
                # incarnation (docs/robustness.md "Control-plane
                # recovery")
                sc_inc0, sc_map0 = sched.incarnation, sched.map_epoch
                sc_port = sched.port
                sched.crash()
                # steps THROUGH the outage, before the successor even
                # binds: the data plane must not notice the control
                # plane is gone
                for i in range(n_shards):
                    grad = 2.0 * ws[i]
                    agg = np.asarray(bps.push_pull(
                        grad, name=f"chaos_soak.w{i}", average=True
                    ))
                    np.testing.assert_array_equal(agg, grad)
                    ws[i] = ws[i] - lr * agg
                live = servers - (1 if 0 <= crash_at <= step else 0)
                sched = Scheduler(
                    num_workers=1, num_servers=live,
                    host="127.0.0.1", port=sc_port,
                )
                sched.start()
                # every node must re-register within the rejoin window
                deadline = _time.monotonic() + 12
                while _time.monotonic() < deadline:
                    with sched._lock:
                        if sched._addrbook_sent:
                            break
                    _time.sleep(0.05)
                assert sched._addrbook_sent, (
                    "fleet never re-registered with the reborn scheduler"
                )
                assert sched.incarnation > sc_inc0, "incarnation not minted"
                assert sched.map_epoch > sc_map0, (
                    f"reborn scheduler's map epoch {sched.map_epoch} did "
                    f"not fence above the reported {sc_map0}"
                )
                sched_reborn = True
            if reshard and step == up_at:
                # live scale-UP: declare the bigger topology from the
                # live worker (the scheduler parks the reply until the
                # joiner registers), then start the joiner — old owners
                # migrate each re-homed key's state, NO re-init barrier
                os.environ["DMLC_NUM_SERVER"] = str(servers + 1)
                rt = threading.Thread(
                    target=client.request_resize,
                    kwargs={"num_servers": servers + 1}, daemon=True,
                )
                rt.start()
                deadline = _time.monotonic() + 10
                while _time.monotonic() < deadline:
                    with sched._lock:
                        if sched.num_servers == servers + 1:
                            break
                    _time.sleep(0.05)
                extra = PSServer(Config.from_env())
                threading.Thread(target=extra.start, daemon=True).start()
                rt.join(timeout=30)
                if rt.is_alive():
                    raise RuntimeError("scale-up resize never completed")
            if reshard and step == down_at and extra is not None:
                # live scale-DOWN: the highest-ranked server (the joiner)
                # gets a drain book, ships every key out, stops itself
                client.request_resize(num_servers=servers)
        if reshard and extra is not None:
            # the drained joiner must stop ITSELF once its store empties
            deadline = _time.monotonic() + 15
            while (not extra._stop.is_set()
                   and _time.monotonic() < deadline):
                _time.sleep(0.1)
            drained_ok = extra._stop.is_set()
        loss1 = float(sum(w @ w for w in ws))
        snap = bps.get_robustness_counters()
        resize_gen = getattr(client, "server_generation", 0) if reshard else 0
        tuner_sweeps = tuner_actions = tuner_rollbacks = 0
        if autotune:
            assert sched.tuner is not None, "BYTEPS_AUTOTUNE did not arm"
            tuner_sweeps = sched.tuner._sweep_idx
            tuner_actions = len(sched.tuner.actions)
            tuner_rollbacks = len(sched.tuner.rollbacks)
    finally:
        bps.shutdown()
        for srv in fleet:
            srv.stop()
        if extra is not None:
            extra.stop()
        sched.stop()

    assert loss1 < loss0, f"loss did not decrease: {loss0} -> {loss1}"
    chaos_on = one_sided or payload_corrupt or any(
        (drop, delay, disconnect, truncate, corrupt)
    )
    injected = sum(v for k, v in snap.items() if k.startswith("chaos_"))
    if chaos_on:
        assert injected > 0, f"no faults injected: {snap}"
    if payload_corrupt and checksums:
        # the wire-integrity contract (docs/robustness.md "Wire
        # integrity"): every injected flip was caught somewhere — the
        # Python side's labeled counter or the native engine's — and
        # every drop healed inside the retry budget (no give-ups, no
        # silent corruption: the per-step bitwise assert above already
        # proved the sums).
        flips = snap.get("chaos_payload_corrupt", 0)
        assert flips > 0, f"--corrupt schedule injected nothing: {snap}"
        caught = (snap.get("wire_checksum_fail", 0)
                  + snap.get("native_checksum_fail", 0))
        assert caught > 0, (
            f"payload flips were injected but no receiver caught them: {snap}"
        )
        assert snap.get("rpc_giveup", 0) == 0, (
            f"corruption drops exhausted a retry budget: {snap}"
        )
    if one_sided:
        # the targeted drops must have exhausted at least one retry
        # budget and routed through the in-place heal (no re-init)
        assert snap.get("resync_attempt", 0) >= 1, (
            f"one-sided schedule never reached the heal path: {snap}"
        )
    if crash_at >= 0 and servers > 1:
        assert snap.get("server_evicted", 0) >= 1, f"no eviction seen: {snap}"
    if sched_crash >= 0:
        # control-plane recovery contract: full membership re-established
        # against the new incarnation (asserted in-loop), with ZERO
        # spurious evictions at rebirth — only a server deliberately
        # crashed AFTER the restart may appear in the reborn totals
        assert sched_reborn, "scheduler was never restarted"
        assert sched.eviction_totals["worker"] == 0, (
            f"spurious worker eviction at rebirth: {sched.eviction_totals}"
        )
        expected_srv_evictions = 1 if crash_at > sched_crash else 0
        assert sched.eviction_totals["server"] == expected_srv_evictions, (
            f"spurious server eviction at rebirth: {sched.eviction_totals}"
        )
        # every node (1 worker + the live servers) rejoined via the
        # reconnect machine, and nobody fell back to the terminal latch
        assert snap.get("sched_rejoin", 0) >= 2, (
            f"nodes did not rejoin through the reconnect machine: {snap}"
        )
    if reshard:
        # both resizes were LIVE migrations: keys moved between owners
        # with their ledgers, every pull above stayed bitwise, and the
        # client never bumped its server generation (no re-init barrier
        # fired for migrated keys — docs/robustness.md "migration flow")
        assert snap.get("migration_keys_moved", 0) > 0, (
            f"reshard schedule moved no keys: {snap}"
        )
        assert snap.get("migration_keys_received", 0) > 0, snap
        assert resize_gen == 0, (
            f"a re-init barrier fired during live resharding "
            f"(server_generation={resize_gen})"
        )
        assert drained_ok, "drained server never stopped itself"
    if autotune:
        # the control loop actually ran while every bitwise/exactly-once
        # invariant above held; any action it took rode the same
        # adopt/migrate planes the soak already proves out
        assert tuner_sweeps > 0, "autotuner never swept"
    return {
        "steps": steps,
        "loss0": loss0,
        "loss1": loss1,
        "counters": snap,
        "tuner": {
            "sweeps": tuner_sweeps,
            "actions": tuner_actions,
            "rollbacks": tuner_rollbacks,
        } if autotune else None,
    }


def run_multi_tenant_soak(
    steps: int = 60,
    seed: int = 7,
    servers: int = 2,
    drop: float = 0.05,
    delay: float = 0.05,
    dim: int = 1024,
) -> dict:
    """Two concurrent JOBS through chaos faults on one PS fleet
    (docs/async.md): job 1 trains SYNC (per-step aggregation must stay
    BITWISE — a cross-tenant key collision or a double-summed replay
    shows up immediately), job 2 trains ASYNC (the server's
    authoritative store must equal the exact running sum of every
    applied push, and its version must advance once per push — lost
    pushes and broken ledger dedupe both break the equality).  Faults
    are retryable classes only (drop/delay): a degraded re-init reset
    is a legitimate fallback but would wipe the async store's history
    and turn this invariant check into noise."""
    os.environ.update(
        {
            "BYTEPS_VAN": "chaos:tcp",
            "BYTEPS_CHAOS_SEED": str(seed),
            "BYTEPS_CHAOS_DROP": str(drop),
            "BYTEPS_CHAOS_DELAY": str(delay),
            "BYTEPS_CHAOS_DELAY_MS": "10",
            "BYTEPS_CHAOS_DISCONNECT": "0",
            "BYTEPS_CHAOS_TRUNCATE": "0",
            "BYTEPS_CHAOS_CORRUPT": "0",
            "BYTEPS_RPC_DEADLINE_S": "0.3",
            "BYTEPS_INIT_DEADLINE_S": "0.5",
            "BYTEPS_RPC_RETRIES": "8",
            "BYTEPS_RPC_BACKOFF_S": "0.05",
            "BYTEPS_CONNECT_RETRY_S": "0.2",
            "BYTEPS_HEARTBEAT_INTERVAL": "0.5",
            "BYTEPS_FORCE_DISTRIBUTED": "1",
            "DMLC_NUM_WORKER": "1",
            "DMLC_NUM_SERVER": str(servers),
            "DMLC_PS_ROOT_URI": "127.0.0.1",
        }
    )

    from byteps_tpu.common.config import Config
    from byteps_tpu.common.tenancy import job_of_key
    from byteps_tpu.comm.rendezvous import Scheduler
    from byteps_tpu.core.telemetry import counters
    from byteps_tpu.server.server import PSServer

    counters().reset()
    sched = Scheduler(num_workers=1, num_servers=servers, host="127.0.0.1")
    sched.start()
    os.environ["DMLC_PS_ROOT_PORT"] = str(sched.port)
    fleet = [PSServer(Config.from_env()) for _ in range(servers)]
    for srv in fleet:
        threading.Thread(target=srv.start, daemon=True).start()

    import byteps_tpu as bps
    from byteps_tpu.common.registry import get_registry

    rng = np.random.default_rng(seed)
    w_sync = rng.standard_normal(dim).astype(np.float32)
    loss0 = float(w_sync @ w_sync)
    lr = 0.05
    running = np.zeros(dim, dtype=np.float32)
    try:
        bps.init()
        # one worker PROCESS hosting two tenants via the per-tensor
        # declare hooks: job 1 sync, job 2 async (unbounded staleness)
        get_registry().declare("mt.sync", byteps_job="1")
        get_registry().declare(
            "mt.async", byteps_job="2", byteps_async="1",
            byteps_staleness="-1",
        )
        for step in range(steps):
            # --- sync tenant: per-step bitwise aggregation ---
            grad = 2.0 * w_sync
            agg = np.asarray(bps.push_pull(grad, name="mt.sync",
                                           average=True))
            np.testing.assert_array_equal(agg, grad)
            w_sync = w_sync - lr * agg
            # --- async tenant: the pulled state must equal the exact
            # running sum of the applied pushes (same accumulation
            # order server-side: store += delta per push) ---
            delta = rng.standard_normal(dim).astype(np.float32)
            pulled = np.asarray(bps.push_pull(delta, name="mt.async",
                                              average=False))
            running = running + delta
            np.testing.assert_array_equal(pulled, running)
        loss1 = float(w_sync @ w_sync)
        snap = bps.get_robustness_counters()
        # monotone version progress on the async key: exactly one
        # applied push per round — a lost push OR a double-summed
        # replay would leave store_version != steps
        async_states = [
            (key, ks) for srv in fleet for key, ks in srv._keys.items()
            if ks.store is not None and job_of_key(key) == 2
        ]
        assert async_states, "async tenant's key never materialized"
        for key, ks in async_states:
            assert ks.async_mode, f"key {key:#x} lost its async profile"
            assert ks.store_version == steps, (
                f"async key {key:#x}: store_version {ks.store_version} "
                f"!= {steps} applied pushes (lost push or broken dedupe)"
            )
    finally:
        bps.shutdown()
        for srv in fleet:
            srv.stop()
        sched.stop()

    assert loss1 < loss0, f"sync tenant did not learn: {loss0} -> {loss1}"
    injected = sum(v for k, v in snap.items() if k.startswith("chaos_"))
    if drop or delay:
        assert injected > 0, f"no faults injected: {snap}"
    return {
        "steps": steps,
        "loss0": loss0,
        "loss1": loss1,
        "counters": snap,
    }


def run_server_opt_soak(
    steps: int = 40,
    seed: int = 7,
    servers: int = 2,
    drop: float = 0.05,
    delay: float = 0.05,
    dim: int = 1536,
    reshard: bool = True,
) -> dict:
    """Server-side optimizer plane under seeded chaos (docs/
    architecture.md "Server-side optimizer"): momentum- and adam-updated
    keys train through drops/delays — and, with ``reshard``, through a
    live scale-up + scale-down that migrates optimizer slots and step
    counts mid-trajectory — while a local mirror of each key's rule
    asserts the pulled PARAMETERS are bitwise every single step.

    Exactly-once under replay is asserted two ways at exit: every
    surviving key's ``opt_step`` is exactly 1 (seed) + ``steps``
    gradient rounds, and the fleet-wide ``server_opt_updates`` total is
    exactly ``steps * n_shards`` — a replayed push that re-fired a rule
    anywhere would break both (and the bitwise params first)."""
    if reshard and servers < 2:
        raise ValueError("--server-opt reshard needs --servers >= 2")
    os.environ.update(
        {
            "BYTEPS_VAN": "chaos:tcp",
            "BYTEPS_CHAOS_SEED": str(seed),
            "BYTEPS_CHAOS_DROP": str(drop),
            "BYTEPS_CHAOS_DELAY": str(delay),
            "BYTEPS_CHAOS_DELAY_MS": "10",
            "BYTEPS_CHAOS_DISCONNECT": "0",
            "BYTEPS_CHAOS_TRUNCATE": "0",
            "BYTEPS_CHAOS_CORRUPT": "0",
            "BYTEPS_CHAOS_PAYLOAD_CORRUPT": "0",
            "BYTEPS_RPC_DEADLINE_S": "0.3",
            "BYTEPS_INIT_DEADLINE_S": "0.5",
            "BYTEPS_RPC_RETRIES": "8",
            "BYTEPS_RPC_BACKOFF_S": "0.05",
            "BYTEPS_CONNECT_RETRY_S": "0.2",
            "BYTEPS_DEGRADED_STEP_RETRIES": "8",
            "BYTEPS_HEARTBEAT_INTERVAL": "0.5",
            "BYTEPS_FORCE_DISTRIBUTED": "1",
            "BYTEPS_ELASTIC_RESHARD": "1" if reshard else "0",
            "DMLC_NUM_WORKER": "1",
            "DMLC_NUM_SERVER": str(servers),
            "DMLC_PS_ROOT_URI": "127.0.0.1",
        }
    )

    from byteps_tpu.common.config import Config
    from byteps_tpu.comm.rendezvous import Scheduler
    from byteps_tpu.core.telemetry import counters
    from byteps_tpu.server.server import PSServer
    from byteps_tpu.server.update_rules import make_rule

    counters().reset()
    sched = Scheduler(num_workers=1, num_servers=servers, host="127.0.0.1")
    sched.start()
    os.environ["DMLC_PS_ROOT_PORT"] = str(sched.port)
    fleet = [PSServer(Config.from_env()) for _ in range(servers)]
    for srv in fleet:
        threading.Thread(target=srv.start, daemon=True).start()

    import time as _time

    import byteps_tpu as bps

    # several named shards so the ring re-homes a real subset on every
    # resize; half momentum (one slot) and half adam (two slots + the
    # bias-correction step count) so the migration tail carries every
    # slot shape this plane ships
    shards = [
        ("momentum", {"lr": 0.02}), ("momentum", {"lr": 0.02}),
        ("momentum", {"lr": 0.02}), ("adam", {"lr": 0.01}),
        ("adam", {"lr": 0.01}), ("adam", {"lr": 0.01}),
    ]
    n_shards = len(shards)
    sdim = max(4, dim // n_shards)
    rng = np.random.default_rng(seed)
    ws = [rng.standard_normal(sdim).astype(np.float32) for _ in shards]
    loss0 = float(sum(w @ w for w in ws))
    # the local mirror: the SAME rule classes, applied to a copy — any
    # divergence between it and the pulled params is a wire/replay bug
    refs = [make_rule(rule, hp, sdim, np.float32) for rule, hp in shards]
    ref_t = 0
    up_at, down_at = max(1, steps // 3), max(2, (2 * steps) // 3)
    extra = None
    drained_ok = True
    try:
        bps.init()
        client = None
        if reshard:
            from byteps_tpu.core.state import get_state

            client = get_state().engine.client
        for i, (rule, hp) in enumerate(shards):
            bps.declare_tensor(f"sopt_soak.w{i}", byteps_server_opt=rule,
                               byteps_server_opt_hp=hp)
        # seed round: push the initial params, get them back VERBATIM
        for i, w in enumerate(ws):
            got = np.asarray(bps.push_pull(w, name=f"sopt_soak.w{i}"))
            np.testing.assert_array_equal(got, w)
        for step in range(steps):
            ref_t += 1
            for i in range(n_shards):
                grad = 2.0 * ws[i]  # d/dw ||w||²
                got = np.asarray(
                    bps.push_pull(grad, name=f"sopt_soak.w{i}")
                )
                # mirror the server: rule.apply mutates our copy with
                # the identical float32 op order — the pull must match
                # bitwise, every step, through every fault and migration
                refs[i].apply(ws[i], grad.copy(), 1, ref_t)
                np.testing.assert_array_equal(got, ws[i])
            if reshard and step == up_at:
                os.environ["DMLC_NUM_SERVER"] = str(servers + 1)
                rt = threading.Thread(
                    target=client.request_resize,
                    kwargs={"num_servers": servers + 1}, daemon=True,
                )
                rt.start()
                deadline = _time.monotonic() + 10
                while _time.monotonic() < deadline:
                    with sched._lock:
                        if sched.num_servers == servers + 1:
                            break
                    _time.sleep(0.05)
                extra = PSServer(Config.from_env())
                threading.Thread(target=extra.start, daemon=True).start()
                rt.join(timeout=30)
                if rt.is_alive():
                    raise RuntimeError("scale-up resize never completed")
            if reshard and step == down_at and extra is not None:
                client.request_resize(num_servers=servers)
        if reshard and extra is not None:
            deadline = _time.monotonic() + 15
            while (not extra._stop.is_set()
                   and _time.monotonic() < deadline):
                _time.sleep(0.1)
            drained_ok = extra._stop.is_set()
        loss1 = float(sum(w @ w for w in ws))
        snap = bps.get_robustness_counters()
        updates = counters().snapshot().get("server_opt_updates", 0)
        # exactly-once at the state level: every surviving key carries
        # exactly seed + `steps` applied rounds, and its slots match the
        # local mirror bitwise (migration moved them, replay never
        # re-fired them)
        live = []
        for srv in fleet + ([extra] if extra is not None else []):
            for key, ks in srv._keys.items():
                if ks.opt_rule is not None and ks.migrated_to is None:
                    live.append((key, ks))
        assert len(live) == n_shards, (
            f"{len(live)} live server-opt keys, expected {n_shards}"
        )
        for key, ks in live:
            assert ks.opt_step == steps + 1, (
                f"key {key:#x}: opt_step {ks.opt_step} != {steps + 1} "
                "(a replayed push re-fired the rule, or a round was lost)"
            )
    finally:
        bps.shutdown()
        for srv in fleet:
            srv.stop()
        if extra is not None:
            extra.stop()
        sched.stop()

    assert loss1 < loss0, f"loss did not decrease: {loss0} -> {loss1}"
    assert updates == steps * n_shards, (
        f"server_opt_updates {updates} != {steps * n_shards} "
        "(exactly-once violated: a rule fired twice or never)"
    )
    if drop or delay:
        injected = sum(v for k, v in snap.items() if k.startswith("chaos_"))
        assert injected > 0, f"no faults injected: {snap}"
    if reshard:
        assert snap.get("migration_keys_moved", 0) > 0, (
            f"reshard schedule moved no keys: {snap}"
        )
        assert drained_ok, "drained server never stopped itself"
    return {
        "steps": steps,
        "loss0": loss0,
        "loss1": loss1,
        "counters": snap,
        "server_opt_updates": updates,
    }


def run_corrupt_ab(args) -> int:
    """The two-leg corruption proof (docs/robustness.md "Wire
    integrity"), each leg a fresh subprocess (the soak mutates
    process-wide env): the SAME seeded payload-flip schedule must
    survive bitwise with checksums on, and demonstrably corrupt with
    checksums off — detection, not luck."""
    import subprocess

    base = [
        sys.executable, os.path.abspath(__file__),
        "--steps", str(args.steps), "--seed", str(args.seed),
        "--servers", str(args.servers),
        "--drop", "0", "--delay", "0", "--disconnect", "0",
        "--truncate", "0", "--corrupt-frame", "0",
        "--corrupt", "--engine", args.engine,
        "--timeout", str(args.timeout),
    ]
    if args.reshard:
        base.append("--reshard")
    print(f"[A/B] leg A: checksums ON (seed={args.seed}, "
          f"engine={args.engine}) ...")
    a = subprocess.run(base, capture_output=True, text=True,
                       timeout=args.timeout + 120)
    print(a.stdout.strip())
    if a.returncode != 0:
        print(a.stderr.strip())
        print("[A/B] FAILED: the checksums-ON leg did not survive")
        return 1
    print(f"[A/B] leg B: SAME schedule, checksums OFF ...")
    b = subprocess.run(base + ["--no-checksum"], capture_output=True,
                       text=True, timeout=args.timeout + 120)
    if b.returncode == 0:
        print(b.stdout.strip())
        print("[A/B] FAILED: the checksums-OFF leg survived bitwise — "
              "the injected flips were inert, so leg A proves nothing")
        return 1
    tail = (b.stdout.strip().splitlines() or ["<no output>"])[-1]
    print(f"[A/B] leg B corrupted as expected: {tail}")
    print("[A/B] OK: checksums-on survives bitwise, checksums-off "
          "corrupts — detection is the checksum's doing, not luck")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--servers", type=int, default=2)
    ap.add_argument("--drop", type=float, default=0.05)
    ap.add_argument("--delay", type=float, default=0.05)
    ap.add_argument("--disconnect", type=float, default=0.005)
    ap.add_argument("--truncate", type=float, default=0.005)
    ap.add_argument("--corrupt-frame", type=float, default=0.005,
                    help="probability of the magic-byte flip (header "
                         "corruption — always detected by framing)")
    ap.add_argument("--corrupt", action="store_true",
                    help="payload-corruption mode (docs/robustness.md "
                         "'Wire integrity'): seeded single-bit flips past "
                         "the header at p=0.05 on PUSH/PULL/FUSED (plus "
                         "MIGRATE_STATE with --reshard) with "
                         "BYTEPS_WIRE_CHECKSUM=1 and fusion armed; asserts "
                         "bitwise pulls every step, wire_checksum_fail>0, "
                         "rpc_giveup==0")
    ap.add_argument("--no-checksum", action="store_true",
                    help="with --corrupt: run the SAME seeded flip schedule "
                         "with the integrity plane OFF — the run is "
                         "expected to FAIL (silent corruption); used by "
                         "--ab's control leg")
    ap.add_argument("--engine", choices=("python", "native"),
                    default="python",
                    help="server engine for the fleet (native verifies "
                         "ahead of its stripe rings via the same shared "
                         "wire.h CRC32C)")
    ap.add_argument("--ab", action="store_true",
                    help="with --corrupt: run BOTH legs in subprocesses — "
                         "checksums on must survive bitwise, the same "
                         "schedule with checksums off must corrupt (the "
                         "A/B that proves detection, not luck)")
    ap.add_argument("--crash-at", type=int, default=-1,
                    help="step at which to hard-kill the last server")
    ap.add_argument("--one-sided", action="store_true",
                    help="target seeded drops at the single worker→owner-"
                         "server connection so the in-place heal (resync "
                         "+ journal replay) is exercised end-to-end")
    ap.add_argument("--reshard", action="store_true",
                    help="live elastic resharding rehearsal: add a server "
                         "mid-run, then remove one — keys migrate with "
                         "their ledgers (BYTEPS_ELASTIC_RESHARD), every "
                         "pull stays bitwise, no re-init barrier fires")
    ap.add_argument("--sched-crash", type=int, default=-1,
                    help="step at which to hard-kill the scheduler and "
                         "restart it on the same address: training must "
                         "step bitwise through the outage, every node "
                         "rejoin the new incarnation within the grace "
                         "window with zero spurious evictions, and a "
                         "subsequent --reshard scale-up still work "
                         "against the reborn scheduler")
    ap.add_argument("--autotune", action="store_true",
                    help="arm the adaptive control plane (BYTEPS_AUTOTUNE, "
                         "docs/autotune.md) with fast sweep knobs: the "
                         "soak's bitwise/exactly-once invariants must hold "
                         "while the tuner sweeps (and possibly rebalances "
                         "hot keys) under the same seeded faults — "
                         "composes with --reshard")
    ap.add_argument("--server-opt", action="store_true",
                    help="server-side optimizer soak (docs/architecture.md "
                         "\"Server-side optimizer\"): momentum + adam keys "
                         "updated ON the servers through seeded drops/"
                         "delays — and through a live reshard when "
                         "--reshard (default on for this mode) — while a "
                         "local rule mirror asserts the pulled params are "
                         "bitwise every step and the exit asserts exactly-"
                         "once rule firing (opt_step, server_opt_updates); "
                         "Python engine only")
    ap.add_argument("--no-reshard", action="store_true",
                    help="with --server-opt: skip the mid-run scale-up/"
                         "scale-down (slots then never migrate)")
    ap.add_argument("--multi-tenant", action="store_true",
                    help="two concurrent jobs (sync + async, "
                         "job-namespaced keys) through chaos faults on "
                         "one fleet: per-job bitwise correctness in sync "
                         "mode, exact running-sum state + monotone "
                         "version progress in async mode (docs/async.md)")
    ap.add_argument("--timeout", type=float, default=300.0,
                    help="watchdog: the soak must finish within this")
    args = ap.parse_args()

    if args.ab:
        if not args.corrupt:
            ap.error("--ab needs --corrupt")
        return run_corrupt_ab(args)

    result: dict = {}
    err: list = []

    if args.server_opt and args.engine == "native":
        ap.error("--server-opt needs the Python engine (the native server "
                 "rejects the optimizer profile, see docs/robustness.md)")

    def body() -> None:
        try:
            if args.server_opt:
                result.update(
                    run_server_opt_soak(
                        steps=args.steps, seed=args.seed,
                        servers=args.servers, drop=args.drop,
                        delay=args.delay,
                        reshard=not args.no_reshard,
                    )
                )
                return
            if args.multi_tenant:
                result.update(
                    run_multi_tenant_soak(
                        steps=args.steps, seed=args.seed,
                        servers=args.servers, drop=args.drop,
                        delay=args.delay,
                    )
                )
                return
            result.update(
                run_soak(
                    steps=args.steps, seed=args.seed, servers=args.servers,
                    drop=args.drop, delay=args.delay,
                    disconnect=args.disconnect, truncate=args.truncate,
                    corrupt=args.corrupt_frame, crash_at=args.crash_at,
                    one_sided=args.one_sided, reshard=args.reshard,
                    sched_crash=args.sched_crash, autotune=args.autotune,
                    payload_corrupt=args.corrupt,
                    checksums=not args.no_checksum, engine=args.engine,
                )
            )
        except BaseException as e:  # noqa: BLE001
            err.append(e)

    t = threading.Thread(target=body, daemon=True)
    t.start()
    t.join(timeout=args.timeout)
    if t.is_alive():
        print(f"CHAOS SOAK HUNG (seed={args.seed})")
        return 2
    if err:
        print(f"CHAOS SOAK FAILED (seed={args.seed}): {err[0]!r}")
        return 1
    print(
        "CHAOS SOAK OK: steps=%d loss %.1f -> %.3g faults=%s"
        % (
            result["steps"], result["loss0"], result["loss1"],
            {k: v for k, v in sorted(result["counters"].items())},
        )
    )
    if result.get("tuner"):
        print("AUTOTUNE: %(sweeps)d sweeps, %(actions)d action(s), "
              "%(rollbacks)d rollback(s)" % result["tuner"])
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
