"""Cluster health CLI: query the scheduler's heartbeat table.

    python tools/check_cluster.py [--uri 127.0.0.1] [--port 9000] \
        [--dead-after 30]

Prints per-node heartbeat ages (seconds since last message) and exits
nonzero if any node's age exceeds ``--dead-after`` — pluggable into any
watchdog/orchestrator (the failure-detection policy layer, SURVEY §5.3).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from byteps_tpu.comm.transport import (
    Message,
    Op,
    connect,
    decode_liveness,
    recv_message,
    send_message,
)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--uri", default=os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1"))
    ap.add_argument("--port", type=int,
                    default=int(os.environ.get("DMLC_PS_ROOT_PORT", "9000")))
    ap.add_argument("--dead-after", type=float, default=30.0)
    args = ap.parse_args()

    try:
        sock = connect(args.uri, args.port, timeout=5)
    except OSError as e:
        print(f"scheduler unreachable at {args.uri}:{args.port}: {e}")
        return 2
    send_message(sock, Message(Op.QUERY, seq=1))
    live = decode_liveness(recv_message(sock).payload)
    sock.close()

    rc = 0
    for role in ("worker", "server"):
        nodes = live.get(role, {})
        if not nodes:
            print(f"{role}s: none registered")
            continue
        for rank in sorted(nodes):
            age = nodes[rank]
            state = "OK" if age <= args.dead_after else "DEAD?"
            if state != "OK":
                rc = 1
            print(f"{role}[{rank}]: last heartbeat {age:6.1f}s ago  {state}")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
