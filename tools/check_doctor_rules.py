#!/usr/bin/env python
"""CI guard: bps_doctor's rule table and the troubleshooting field guide
may never drift apart.

The doctor (tools/bps_doctor.py) is docs/troubleshooting.md made
executable — which only stays true if the binding is enforced, the same
way tools/check_metrics_doc.py pins metric names and
tools/check_env_doc.py pins env knobs.  Two directions:

1. **rule → doc**: every rule's ``anchor`` must name a REAL heading in
   docs/troubleshooting.md (slugs computed with the doctor's own
   ``slugify``, so the two can't disagree), and every rule must be
   cited by at least one ``<!-- rule: <name> -->`` marker in the doc.
2. **doc → rule**: every row of a field-guide table (the table
   following a ``<!-- doctor: field-guide -->`` sentinel) must carry a
   ``<!-- rule: <name> -->`` marker naming an existing rule, or an
   explicit ``<!-- no-rule: <reason> -->`` waiver — a failure mode
   documented for humans but not codified for the doctor is a
   conscious decision, never an accident.

Wired into tier-1 as
``tests/test_observability.py::test_doctor_rules_complete``.

Usage: ``python tools/check_doctor_rules.py [--repo ROOT]``
"""

from __future__ import annotations

import argparse
import importlib.util
import os
import re
import sys

_HEADING_RE = re.compile(r"^#{1,6}\s+(.*)$")
_RULE_MARK_RE = re.compile(r"<!--\s*rule:\s*([a-z0-9_]+)\s*-->")
_WAIVER_RE = re.compile(r"<!--\s*no-rule:\s*([^>]+?)\s*-->")
_SENTINEL = "<!-- doctor: field-guide -->"


def load_doctor(repo: str):
    path = os.path.join(repo, "tools", "bps_doctor.py")
    spec = importlib.util.spec_from_file_location("bps_doctor", path)
    mod = importlib.util.module_from_spec(spec)
    # register BEFORE exec: dataclass processing resolves the module via
    # sys.modules on 3.10, and an unregistered module breaks it
    sys.modules.setdefault("bps_doctor", mod)
    spec.loader.exec_module(mod)
    return sys.modules["bps_doctor"]


def check(repo: str) -> list:
    """Returns a list of problem strings (empty = green)."""
    problems = []
    doctor = load_doctor(repo)
    rules = {r.name: r for r in doctor.RULES}
    doc_path = os.path.join(repo, "docs", "troubleshooting.md")
    if not os.path.exists(doc_path):
        return [f"{doc_path} missing"]
    with open(doc_path) as f:
        lines = f.read().splitlines()

    slugs = {
        doctor.slugify(m.group(1))
        for line in lines
        if (m := _HEADING_RE.match(line)) is not None
    }
    cited = set()
    for line in lines:
        for name in _RULE_MARK_RE.findall(line):
            cited.add(name)
            if name not in rules:
                problems.append(
                    f"doc cites unknown rule {name!r} "
                    "(markers must name a tools/bps_doctor.py RULES entry)"
                )

    # rule → doc
    for name, rule in rules.items():
        anchor = rule.anchor
        if "#" in anchor:
            anchor = anchor.split("#", 1)[1]
        if anchor not in slugs:
            problems.append(
                f"rule {name!r} anchors to #{anchor}, which is not a "
                "heading in docs/troubleshooting.md"
            )
        if name not in cited:
            problems.append(
                f"rule {name!r} is never cited by a <!-- rule: … --> "
                "marker in docs/troubleshooting.md — the field guide "
                "doesn't know this failure mode exists"
            )

    # doc → rule: every field-guide table row is marked or waived
    i = 0
    saw_sentinel = False
    while i < len(lines):
        if _SENTINEL not in lines[i]:
            i += 1
            continue
        saw_sentinel = True
        i += 1
        # skip to the table (blank lines allowed between)
        while i < len(lines) and not lines[i].lstrip().startswith("|"):
            if lines[i].strip() and not lines[i].lstrip().startswith("<!--"):
                problems.append(
                    f"{_SENTINEL} at line {i} is not followed by a table"
                )
                break
            i += 1
        header_seen = 0
        while i < len(lines) and lines[i].lstrip().startswith("|"):
            row = lines[i]
            i += 1
            if header_seen < 2:
                # header + |---| separator rows carry no failure mode
                header_seen += 1
                continue
            if _RULE_MARK_RE.search(row) or _WAIVER_RE.search(row):
                continue
            cell = row.split("|")[1].strip() if "|" in row else row
            problems.append(
                "field-guide row without a <!-- rule: … --> marker or "
                f"<!-- no-rule: … --> waiver: {cell[:70]!r}"
            )
    if not saw_sentinel:
        problems.append(
            f"docs/troubleshooting.md has no {_SENTINEL} sentinel — the "
            "doctor's field guide table is unmarked"
        )
    return problems


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--repo",
        default=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    args = ap.parse_args(argv)
    problems = check(args.repo)
    if problems:
        print("doctor rules and docs/troubleshooting.md have drifted:",
              file=sys.stderr)
        for p in problems:
            print(f"  {p}", file=sys.stderr)
        return 1
    doctor = load_doctor(args.repo)
    print(f"doctor rules OK: {len(doctor.RULES)} rule(s) bound to the "
          "field guide")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
