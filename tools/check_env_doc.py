#!/usr/bin/env python
"""CI guard: every ``BYTEPS_*`` knob the code reads must be in docs/env.md.

The configuration surface is pure env vars (docs/env.md), anchored in
``common/config.py`` but with readers spread across the package (vans,
chaos, native autobuild, launcher NUMA planning).  Knobs rot the same way
metric names do (tools/check_metrics_doc.py): a feature lands with its
``os.environ.get("BYTEPS_...")`` and the table is forgotten.  This guard
scans every env READ —

    os.environ.get("BYTEPS_X") / os.environ["BYTEPS_X"] / os.getenv(...)
    _env_int/_env_bool/_env_str/_env_float("BYTEPS_X", ...)

— across ``byteps_tpu/`` (and ``tools/``, which document their knobs in
the same catalog) and fails (exit 1) listing any name absent from
docs/env.md, where a name counts as documented when it appears inside
backticks.  Wired into tier-1 as
``tests/test_observability.py::test_env_catalog_complete``.

Usage: ``python tools/check_env_doc.py [--repo ROOT]``
"""

from __future__ import annotations

import argparse
import os
import re
import sys

#: an env READ whose first argument is a BYTEPS_* string literal
_READ_RE = re.compile(
    r"(?:environ\.get\(|environ\[|getenv\(|"
    r"_env_int\(|_env_bool\(|_env_str\(|_env_float\()\s*"
    r"[\"'](BYTEPS_[A-Z0-9_]+)[\"']"
)

def discover_read(repo: str) -> dict:
    """{name: [file:line, ...]} for every BYTEPS_* env read in the
    package (and tools/)."""
    found: dict = {}
    for sub in ("byteps_tpu", "tools"):
        base = os.path.join(repo, sub)
        for root, _dirs, files in os.walk(base):
            if "__pycache__" in root:
                continue
            for fn in files:
                # the guard's own docstring quotes the read patterns —
                # scanning itself would demand a fake BYTEPS_X entry
                if not fn.endswith(".py") or fn == "check_env_doc.py":
                    continue
                path = os.path.join(root, fn)
                with open(path) as f:
                    text = f.read()
                for m in _READ_RE.finditer(text):
                    name = m.group(1)
                    line = text[: m.start()].count("\n") + 1
                    rel = os.path.relpath(path, repo)
                    found.setdefault(name, []).append(f"{rel}:{line}")
    return found


def documented_names(repo: str) -> set:
    doc = os.path.join(repo, "docs", "env.md")
    if not os.path.exists(doc):
        return set()
    with open(doc) as f:
        text = f.read()
    names = set()
    for chunk in re.findall(r"`([^`]+)`", text):
        names.update(re.findall(r"BYTEPS_[A-Z0-9_]+", chunk))
    return names


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--repo",
        default=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    args = ap.parse_args(argv)
    read = discover_read(args.repo)
    docs = documented_names(args.repo)
    if not docs:
        print("docs/env.md missing or has no documented BYTEPS_* names",
              file=sys.stderr)
        return 1
    missing = [(n, sites) for n, sites in sorted(read.items()) if n not in docs]
    if missing:
        print("env knobs read by the code but absent from docs/env.md:",
              file=sys.stderr)
        for name, sites in missing:
            print(f"  {name}  ({'; '.join(sites[:3])})", file=sys.stderr)
        return 1
    print(f"env catalog OK: {len(read)} knob(s) read, "
          f"{len(docs)} documented")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
