#!/usr/bin/env python
"""CI guard: every metric the code emits must be in the documented catalog.

Scans ``byteps_tpu/`` for metric registrations/bumps —

    counters().bump("name" ...)        # counters (incl. chaos _bump sites)
    counters().set_floor("name" ...)
    metrics().observe("name" ...)      # histograms
    metrics().histogram("name" ...)
    metrics().gauge_set("name" ...) / gauge_fn("name" ...)

— and fails (exit 1) listing any name absent from the metric catalog in
``docs/observability.md``.  f-string names (``f"fusion_flush_{reason}"``)
are matched by their literal prefix: at least one documented name must
start with it.

The native C++ plane is covered too: every ``"native_*"`` string
literal in ``byteps_tpu/native/*.cc`` (counter names in ps_server.cc's
``kCounterNames``, histogram names at their registration sites) must
appear in the catalog — the GIL-free engines' metric names rot exactly
like the Python ones.  Wired into tier-1 as
``tests/test_observability.py::test_metrics_catalog_complete`` so the
catalog cannot rot.

Usage: ``python tools/check_metrics_doc.py [--repo ROOT]``
"""

from __future__ import annotations

import argparse
import os
import re
import sys

#: call sites that mint a metric name; the first string literal argument
#: is the name.  ``_bump`` covers the chaos van's counter helper.
_CALL_RE = re.compile(
    r"\.(?:bump|_bump|set_floor|observe|histogram|gauge_set|gauge_fn)\(\s*"
    r"(f?)\"([A-Za-z0-9_{}]+)\"",
)

#: metric names in the docs catalog: any backticked word-ish token
_DOC_NAME_RE = re.compile(r"`([a-z][a-z0-9_]*)`")

#: a native metric name minted in C++ — any native_* string literal in
#: the engine sources (counter name tables, histogram registration
#: sites).  The native_ prefix is the naming contract
#: (docs/observability.md), so the literal scan IS the registration scan.
_NATIVE_NAME_RE = re.compile(r"\"(native_[a-z0-9_]+)\"")


def discover_emitted(repo: str) -> dict:
    """{name_or_prefix: [file:line, ...]}; prefixes end with '*'."""
    found: dict = {}
    pkg = os.path.join(repo, "byteps_tpu")
    for root, _dirs, files in os.walk(pkg):
        if "__pycache__" in root:
            continue
        for fn in files:
            path = os.path.join(root, fn)
            if fn.endswith(".cc"):
                # native plane: scan the C++ sources' string literals for
                # native_* metric names (counters + histograms)
                with open(path) as f:
                    text = f.read()
                for m in _NATIVE_NAME_RE.finditer(text):
                    line = text[: m.start()].count("\n") + 1
                    rel = os.path.relpath(path, repo)
                    found.setdefault(m.group(1), []).append(f"{rel}:{line}")
                continue
            if not fn.endswith(".py"):
                continue
            with open(path) as f:
                text = f.read()
            for m in _CALL_RE.finditer(text):
                is_f, name = m.group(1), m.group(2)
                if is_f or "{" in name:
                    # f-string: enforce the literal prefix
                    name = name.split("{", 1)[0]
                    if not name:
                        continue  # fully dynamic: nothing checkable
                    name += "*"
                line = text[: m.start()].count("\n") + 1
                rel = os.path.relpath(path, repo)
                found.setdefault(name, []).append(f"{rel}:{line}")
    return found


def documented_names(repo: str) -> set:
    doc = os.path.join(repo, "docs", "observability.md")
    if not os.path.exists(doc):
        return set()
    with open(doc) as f:
        return set(_DOC_NAME_RE.findall(f.read()))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--repo",
        default=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    args = ap.parse_args(argv)
    emitted = discover_emitted(args.repo)
    docs = documented_names(args.repo)
    if not docs:
        print("docs/observability.md missing or has no catalog entries",
              file=sys.stderr)
        return 1
    missing = []
    for name, sites in sorted(emitted.items()):
        if name.endswith("*"):
            prefix = name[:-1]
            ok = any(d.startswith(prefix) for d in docs)
        else:
            ok = name in docs
        if not ok:
            missing.append((name, sites))
    if missing:
        print("metrics emitted but not documented in docs/observability.md:",
              file=sys.stderr)
        for name, sites in missing:
            print(f"  {name}  ({'; '.join(sites[:3])})", file=sys.stderr)
        return 1
    print(f"metrics catalog OK: {len(emitted)} emitted name(s), "
          f"{len(docs)} documented")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
