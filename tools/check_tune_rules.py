#!/usr/bin/env python
"""CI guard: the autotuner's policy table and docs/autotune.md may never
drift apart.

The adaptive control plane (byteps_tpu/core/autotune.py) is
docs/autotune.md made executable — the same binding the doctor
(tools/check_doctor_rules.py), the metric catalog
(tools/check_metrics_doc.py), and the env catalog
(tools/check_env_doc.py) enforce for their surfaces.  Two directions:

1. **policy → doc + wiring**: every rule named in ``TUNE_RULES`` must
   (a) be cited by a ``<!-- policy: <name> -->`` marker in
   docs/autotune.md (its row of the policy table), and (b) actually be
   wired into the sweep — a ``("<name>", self._policy_...)`` entry in
   ``AutoTuner.sweep`` plus a ``_policy_<name>`` method — so every
   shipped policy really emits ``tune_action{rule=<name>}`` when it
   fires (the label value IS the sweep-table name).
2. **doc → policy**: every ``<!-- policy: … -->`` marker in
   docs/autotune.md must name a ``TUNE_RULES`` entry — a documented
   policy that no longer ships is a lie in the operator's handbook.

Wired into tier-1 as ``tests/test_autotune.py::test_tune_rules_complete``.

Usage: ``python tools/check_tune_rules.py [--repo ROOT]``
"""

from __future__ import annotations

import argparse
import importlib.util
import os
import re
import sys

_POLICY_MARK_RE = re.compile(r"<!--\s*policy:\s*([a-z0-9_]+)\s*-->")


def load_autotune(repo: str):
    path = os.path.join(repo, "byteps_tpu", "core", "autotune.py")
    spec = importlib.util.spec_from_file_location("_bps_autotune_guard", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault("_bps_autotune_guard", mod)
    spec.loader.exec_module(mod)
    return sys.modules["_bps_autotune_guard"]


def check(repo: str) -> list:
    """Returns a list of problem strings (empty = green)."""
    problems = []
    src_path = os.path.join(repo, "byteps_tpu", "core", "autotune.py")
    doc_path = os.path.join(repo, "docs", "autotune.md")
    if not os.path.exists(doc_path):
        return [f"{doc_path} missing"]
    mod = load_autotune(repo)
    rules = tuple(mod.TUNE_RULES)
    with open(src_path) as f:
        src = f.read()
    with open(doc_path) as f:
        doc = f.read()
    cited = set(_POLICY_MARK_RE.findall(doc))

    for name in rules:
        if name not in cited:
            problems.append(
                f"policy {name!r} has no <!-- policy: … --> marker in "
                "docs/autotune.md — the operator handbook doesn't know "
                "this policy exists"
            )
        # the sweep table entry is what stamps tune_action{rule=<name>}
        if not re.search(rf'\(\s*"{name}"\s*,\s*self\._policy_', src):
            problems.append(
                f"policy {name!r} is in TUNE_RULES but not wired into "
                "AutoTuner.sweep — it can never emit "
                f"tune_action{{rule={name}}}"
            )
        if not hasattr(mod.AutoTuner, f"_policy_{name}"):
            problems.append(
                f"policy {name!r} has no AutoTuner._policy_{name} method"
            )

    for name in cited:
        if name not in rules:
            problems.append(
                f"docs/autotune.md cites unknown policy {name!r} "
                "(markers must name a TUNE_RULES entry)"
            )
    return problems


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--repo",
        default=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    args = ap.parse_args(argv)
    problems = check(args.repo)
    if problems:
        print("autotune policies and docs/autotune.md have drifted:",
              file=sys.stderr)
        for p in problems:
            print(f"  {p}", file=sys.stderr)
        return 1
    mod = load_autotune(args.repo)
    print(f"tune rules OK: {len(mod.TUNE_RULES)} policy(ies) bound to "
          "docs/autotune.md")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
