"""One-shot on-chip validation of everything that needs real TPU hardware.

The accelerator tunnel in this environment comes and goes; when it is up,
a single run of this script covers every chip-blocked item:

1. Pallas flash-attention FORWARD compiled on the chip vs the dense
   reference (fp32 tolerance).
2. Pallas flash-attention BACKWARD (blocked dQ/dKV) compiled on the chip
   vs jax.grad of the dense reference.
3. On-device onebit packing: compiled kernel wire bytes vs the C++
   codec's payload for the same input.
4. bench.py's BERT-large step (both configs) — run separately via
   `python bench.py`, noted here for completeness.
5. KV-cached decode throughput vs the recompute path (GPT-2 medium).

    python tools/chip_validation.py [--skip-decode]

Exits nonzero on any mismatch; prints one summary line per item.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np


def check_flash_forward() -> None:
    import jax
    import jax.numpy as jnp

    from byteps_tpu.ops.flash_attention import _dense_reference, flash_attention

    rng = np.random.default_rng(0)
    for causal in (False, True):
        q, k, v = (
            jnp.asarray(rng.normal(size=(2, 4, 256, 64)).astype(np.float32))
            for _ in range(3)
        )
        out = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=causal))(q, k, v)
        ref = _dense_reference(q, k, v, causal, 1.0 / np.sqrt(64))
        # 5e-3 like the backward check: on-chip the blocked online softmax
        # and XLA's dense softmax differ in accumulation order (observed
        # max |diff| ~5e-3 on 0.03% of elements)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=5e-3, atol=5e-3
        )
    print("flash forward compiled on", jax.devices()[0].platform, "OK")


def check_flash_backward() -> None:
    import jax
    import jax.numpy as jnp

    from byteps_tpu.ops.flash_attention import _dense_reference, flash_attention

    rng = np.random.default_rng(1)
    for causal in (False, True):
        q, k, v = (
            jnp.asarray(rng.normal(size=(1, 2, 128, 64)).astype(np.float32))
            for _ in range(3)
        )

        def loss_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=causal) ** 2)

        def loss_dense(q, k, v):
            return jnp.sum(_dense_reference(q, k, v, causal, 1.0 / np.sqrt(64)) ** 2)

        gf = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
        gd = jax.jit(jax.grad(loss_dense, argnums=(0, 1, 2)))(q, k, v)
        # the dense f32 reference is itself ~0.08 max-abs off an f64 ground
        # truth on this geometry while the blocked kernel is ~0.046 (the
        # kernel is the MORE accurate side); 5e-2 abs bounds the dense
        # reference's own error, it is not kernel slack
        for a, b in zip(gf, gd):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-2
            )
    print("flash backward (blocked dQ/dKV) compiled OK")


def check_onebit_device() -> None:
    import jax.numpy as jnp

    from byteps_tpu.native import get_lib
    from byteps_tpu.ops.onebit_device import onebit_compress_device

    lib = get_lib()
    if lib is None:
        print("onebit device: SKIP (native lib unavailable for the oracle)")
        return
    import ctypes

    rng = np.random.default_rng(2)
    # n must be a multiple of 32*1024 or the Pallas kernel path is skipped
    # for the jnp fallback (onebit_device.py:75) — the kernel IS the item
    # under validation here
    n = 32 * 1024 * 2
    x = rng.normal(size=n).astype(np.float32)
    scale, words = onebit_compress_device(jnp.asarray(x), scaling=True)
    out = np.empty(4 + 4 * ((n + 31) // 32), dtype=np.uint8)
    ln = lib.bps_onebit_compress(
        x.ctypes.data_as(ctypes.c_void_p), n,
        out.ctypes.data_as(ctypes.c_void_p), 1,
    )
    ref_scale = np.frombuffer(out[:4].tobytes(), np.float32)[0]
    ref_words = np.frombuffer(out[4:ln].tobytes(), np.uint32)
    # sign words (what the kernel produces) must be byte-exact; the L1
    # scale is an f32 XLA reduction vs the codec's double accumulation —
    # 1-ULP wiggle is expected, not a kernel bug
    np.testing.assert_array_equal(np.asarray(words), ref_words)
    np.testing.assert_allclose(float(scale), ref_scale, rtol=1e-6)
    print("on-device onebit packing matches the C++ codec OK "
          f"(n={n}, words byte-exact, scale within 1e-6)")


def check_device_codec_pipeline() -> None:
    """r5 item: the ENGINE's device-codec path on real TPU — a jax Array
    through a fake-cluster push_pull with an onebit config must compress
    on the chip (Pallas packer) before D2H and decode on-chip after H2D,
    matching the host-path result on a sibling key."""
    import threading

    import jax
    import jax.numpy as jnp

    from byteps_tpu.common.config import Config
    from byteps_tpu.comm.rendezvous import Scheduler
    from byteps_tpu.server.server import PSServer

    os.environ["BYTEPS_MIN_COMPRESS_BYTES"] = "0"
    sched = Scheduler(num_workers=1, num_servers=1, host="127.0.0.1")
    sched.start()
    os.environ.update({
        "DMLC_PS_ROOT_URI": "127.0.0.1", "DMLC_PS_ROOT_PORT": str(sched.port),
        "DMLC_NUM_WORKER": "1", "DMLC_NUM_SERVER": "1",
        "BYTEPS_FORCE_DISTRIBUTED": "1",
    })
    srv = PSServer(Config.from_env())
    threading.Thread(target=srv.start, daemon=True).start()
    import byteps_tpu as bps

    bps.init()
    n = 32 * 1024 * 4  # multiple of 32*1024: the Pallas packer engages
    x = np.random.default_rng(9).normal(size=n).astype(np.float32)
    for name in ("chipdc.dev", "chipdc.host"):
        bps.declare_tensor(
            name, byteps_compressor_type="onebit",
            byteps_compressor_onebit_scaling="True",
        )
    out_dev = bps.push_pull(jnp.asarray(x), name="chipdc.dev", average=False)
    out_host = np.asarray(bps.push_pull(x, name="chipdc.host", average=False))
    assert isinstance(out_dev, jax.Array)
    from byteps_tpu.core.state import get_state

    assert get_state().engine._device_codecs, "device codec path not engaged"
    np.testing.assert_allclose(np.asarray(out_dev), out_host, rtol=1e-5, atol=1e-7)
    bps.shutdown()
    srv.stop()
    sched.stop()
    print(f"engine device-codec pipeline on chip OK (n={n}, "
          "device payload == host payload result)")


def check_decode_throughput() -> None:
    import jax
    import jax.numpy as jnp

    from byteps_tpu.models.transformer import (
        build_generate,
        build_generate_cached,
        gpt2_medium,
        init_params,
        shard_params,
    )
    from byteps_tpu.parallel.mesh_utils import make_training_mesh

    cfg = gpt2_medium(max_seq=256, compute_dtype=jnp.bfloat16)
    mesh = make_training_mesh(1, {"dp": 1, "pp": 1, "sp": 1, "tp": 1})
    params = shard_params(init_params(cfg, seed=0, pp_size=1), cfg, mesh)
    prompt = np.ones((4, 16), dtype=np.int32)
    n_new = 64

    gen_cached = build_generate_cached(cfg, mesh)
    # warm with the SAME n_new — the compiled program is keyed on it
    gen_cached(params, prompt, n_new)
    t0 = time.perf_counter()
    out_c = gen_cached(params, prompt, n_new)
    cached_s = time.perf_counter() - t0

    gen_rec = build_generate(cfg, mesh)
    gen_rec(params, prompt, 1)
    t0 = time.perf_counter()
    out_r = gen_rec(params, prompt, n_new)
    recompute_s = time.perf_counter() - t0

    np.testing.assert_array_equal(out_c, out_r)
    print(
        f"cached decode {n_new} tokens: {cached_s:.2f}s vs recompute "
        f"{recompute_s:.2f}s ({recompute_s / max(cached_s, 1e-9):.1f}x), "
        "token-identical OK"
    )


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--skip-decode", action="store_true")
    args = ap.parse_args()

    import jax

    print("devices:", jax.devices())
    if jax.devices()[0].platform != "tpu":
        # off-TPU both Pallas paths silently take their fallbacks — a
        # passing run here would validate nothing
        print("ERROR: not on TPU; the kernels under validation would "
              "silently fall back. Aborting.", file=sys.stderr)
        return 2
    check_flash_forward()
    check_flash_backward()
    check_onebit_device()
    check_device_codec_pipeline()
    if not args.skip_decode:
        check_decode_throughput()
    print("ALL CHIP VALIDATIONS PASSED — also run: python bench.py")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
