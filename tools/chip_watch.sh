#!/bin/bash
# Watch for the accelerator tunnel to come back; when it does, run every
# chip-blocked validation in sequence and log results.  Designed to be
# left running detached (nohup) while CPU-side work continues:
#
#   nohup bash tools/chip_watch.sh >/dev/null 2>&1 &
#   tail -f /tmp/chip_watch.log
#
# The probe is a real tiny computation (device init alone can succeed
# while the data path hangs).  Each stage gets a generous timeout:
# through-tunnel compiles are minutes, not seconds.
set -u
cd "$(dirname "$0")/.."
LOG=${CHIP_WATCH_LOG:-/tmp/chip_watch.log}
export JAX_COMPILATION_CACHE_DIR=${JAX_COMPILATION_CACHE_DIR:-/tmp/jax_cache}

probe() {
  timeout 90 python -c "import jax.numpy as jnp; float(jnp.sum(jnp.ones(4)))" \
    >/dev/null 2>&1
}

DONE_ONCE=0
while true; do
  if probe; then
    echo "$(date -u +%FT%TZ) TUNNEL UP — starting chip runs" >>"$LOG"
    if [ "$DONE_ONCE" = 0 ]; then
      timeout 1800 python -u tools/chip_validation.py --skip-decode >>"$LOG" 2>&1
      echo "kernel validation rc=$?" >>"$LOG"
    fi
    timeout 2400 python -u bench.py >/tmp/bench_out.json 2>/tmp/bench_err.log
    rc=$?
    echo "bench rc=$rc" >>"$LOG"
    cat /tmp/bench_out.json >>"$LOG" 2>/dev/null
    if [ "$DONE_ONCE" = 0 ]; then
      timeout 3000 python -u tools/flash_tune.py >>"$LOG" 2>&1
      echo "flash tune rc=$?" >>"$LOG"
      timeout 3000 python -u tools/chip_validation.py >>"$LOG" 2>&1
      echo "full validation (incl. decode) rc=$?" >>"$LOG"
    fi
    echo "$(date -u +%FT%TZ) chip run sequence complete" >>"$LOG"
    DONE_ONCE=1
    # keep refreshing last_good so the end-of-round bench record is fresh
    sleep 1800
    continue
  fi
  echo "$(date -u +%FT%TZ) tunnel down" >>"$LOG"
  sleep 120
done
