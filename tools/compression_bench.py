"""Compressed wire path A/B — bytes-on-wire, D2H bytes and step time
for {off, 1bit, topk, device-topk} × {fused, unfused} on a shaped
low-bandwidth link.

The matrix the ISSUE 11 tentpole exists for: gradient compression and
small-tensor fusion used to EXCLUDE each other (a compressed partition
always paid its own RPC; a fused frame always shipped raw fp32).  This
bench drives the same deterministic workload — N medium tensors per step
through a live in-process PS cluster over a rate-shaped van
(``BYTEPS_VAN_RATE_MBYTES_S``, the OVERLAP_r05 harness's link model) — in
every combination and reports wire RPC counts, actual bytes on the wire
(``wire_tx/rx_bytes`` counters), device→host traffic (``d2h_bytes``),
and step-latency stats.

    python tools/compression_bench.py [--keys 48] [--bytes 16384]
        [--steps 8] [--threshold 16384] [--rate-mbps 200] [--delay-ms 0.2]
        [--engine python|native] [--skip-auto] [--out COMPRESS_BENCH_r08.json]

Rows per engine:

- ``raw_unfused`` / ``raw_fused``           — the pre-compression pair
- ``onebit_unfused`` / ``onebit_fused``     — 1-bit + error feedback
- ``topk_unfused`` / ``topk_fused``         — top-k (k = 3%)
- ``raw_jax_fused`` — raw with jax-array inputs: the measured raw D2H
  baseline the device rows are judged against
- ``topk_device_unfused`` / ``topk_device_fused`` — bare top-k with
  jax-array inputs, i.e. the DEVICE path (docs/gradient-compression.md
  "Device path"): packing runs before COPYD2H, so ``d2h_bytes`` counts
  wire-sized payloads instead of raw fp32 staging
- ``auto``  — a deliberately LOSS-making codec (topk with k = n, wire
  ratio 2.0) under ``BYTEPS_COMPRESSION_AUTO=1``: the policy disables it
  after the probe rounds and the tail steps run at raw speed

A top-level ``lossless`` section reports the wire lossless container
(docs/gradient-compression.md "Lossless frame compression") on
representative MIGRATE_STATE / RESYNC_STATE bodies — ratio, C/pure
parity, and throughput of both implementations.

Cross-mode assertions: compressed-fused pulls are BITWISE identical to
compressed-unfused pulls (same codec math, different framing — the
device pair included), and the acceptance block checks compressed-fused
beats compressed-unfused on RPC count AND raw-fused on bytes-on-wire,
with a step-time speedup on the bandwidth-bound link; the device rows
must move only wire-sized bytes over D2H.

``--engine native`` reruns the matrix against the GIL-free C++ server
engine and merges under a top-level ``"native"`` key (native responses
bypass the shaper — the within-engine A/B stays fair, the cross-engine
latency comparison carries that caveat, as in fusion_bench.py).
"""

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np


def _reset_runtime() -> None:
    from byteps_tpu.common import config as _config
    from byteps_tpu.common import registry as _registry
    from byteps_tpu.core import state as _state

    _state.shutdown_state()
    _registry.reset_registry()
    _config.clear_config()


def run_mode(codec: str, threshold: int, keys: int, nbytes: int, steps: int,
             rate_mbps: float, delay_ms: float, engine: str,
             auto: bool = False, jax_in: bool = False) -> dict:
    """One cluster bring-up → timed steps → teardown.  ``codec``:
    "" (raw), "onebit", "topk", "topk_bare" (no EF — device-eligible),
    or "topk_full" (the deliberate loss).  ``jax_in`` pushes jax arrays
    instead of numpy — with a bare codec chain that routes the device
    path (packing before D2H)."""
    from byteps_tpu.common.config import Config
    from byteps_tpu.comm.rendezvous import Scheduler
    from byteps_tpu.core.telemetry import counters
    from byteps_tpu.server.server import NativePSServer, PSServer

    n = max(32, nbytes // 4)
    os.environ.update({
        "BYTEPS_VAN": "tcp",
        "BYTEPS_FUSION_THRESHOLD": str(threshold),
        "BYTEPS_FUSION_CYCLE_MS": "2",
        "BYTEPS_VAN_RATE_MBYTES_S": str(rate_mbps),
        "BYTEPS_VAN_DELAY_MS": str(delay_ms),
        "BYTEPS_MIN_COMPRESS_BYTES": "0",
        "BYTEPS_COMPRESSION_AUTO": "1" if auto else "0",
        "BYTEPS_COMPRESSION_AUTO_ROUNDS": "2",
    })
    sched = Scheduler(num_workers=1, num_servers=1, host="127.0.0.1")
    sched.start()
    os.environ.update({
        "DMLC_PS_ROOT_URI": "127.0.0.1",
        "DMLC_PS_ROOT_PORT": str(sched.port),
        "DMLC_NUM_WORKER": "1",
        "DMLC_NUM_SERVER": "1",
        "BYTEPS_FORCE_DISTRIBUTED": "1",
    })
    if engine == "native":
        os.environ["BYTEPS_SERVER_NATIVE"] = "1"
        srv = NativePSServer(Config.from_env())
    else:
        os.environ.pop("BYTEPS_SERVER_NATIVE", None)
        srv = PSServer(Config.from_env())
    threading.Thread(target=srv.start, daemon=True).start()

    import byteps_tpu as bps

    kwargs = {}
    if codec == "onebit":
        kwargs = {"byteps_compressor_type": "onebit",
                  "byteps_compressor_onebit_scaling": "True",
                  "byteps_ef_type": "vanilla"}
    elif codec == "topk":
        kwargs = {"byteps_compressor_type": "topk",
                  "byteps_compressor_k": "0.03",
                  "byteps_ef_type": "vanilla"}
    elif codec == "topk_bare":  # bare chain — device-path eligible
        kwargs = {"byteps_compressor_type": "topk",
                  "byteps_compressor_k": "0.03"}
    elif codec == "topk_full":  # wire ratio 2.0 — the auto row's bait
        kwargs = {"byteps_compressor_type": "topk",
                  "byteps_compressor_k": str(n)}

    if jax_in:
        import jax.numpy as jnp

        def ship(x):
            return jnp.asarray(x)
    else:
        def ship(x):
            return x

    rng = np.random.default_rng(42)
    base = [rng.standard_normal(n).astype(np.float32) for _ in range(keys)]
    names = [f"cb.{i}" for i in range(keys)]
    final = {}
    try:
        bps.init()
        for nm in names:
            if kwargs:
                bps.declare_tensor(nm, **kwargs)
        # warmup round: settles registration and (jax lanes) jit compiles
        hs = [bps.push_pull_async(ship(x), name=nm, average=False)
              for nm, x in zip(names, base)]
        for h in hs:
            bps.synchronize(h)
        # the auto policy's static fast path verdicts at REGISTRATION
        # (docs/gradient-compression.md "Codec auto-selection"), i.e.
        # before the timed window — fold those into the row's count
        pre_auto = counters().snapshot().get("compression_auto_off", 0)
        counters().reset()
        lat = []
        for step in range(steps):
            scale = np.float32(step + 2)
            t0 = time.perf_counter()
            hs = [bps.push_pull_async(ship(x * scale), name=nm, average=False)
                  for nm, x in zip(names, base)]
            outs = [np.asarray(bps.synchronize(h)) for h in hs]
            lat.append(time.perf_counter() - t0)
            if step == steps - 1:
                final = {nm: out for nm, out in zip(names, outs)}
        snap = counters().snapshot()
    finally:
        bps.shutdown()
        _reset_runtime()
        srv.stop()
        sched.stop()
    tail = sorted(lat[len(lat) // 2:])  # post-settle half (auto row)
    slat = sorted(lat)
    return {
        "engine": engine,
        "codec": codec or "raw",
        "fused": threshold > 0,
        "auto": auto,
        "jax_in": jax_in,
        "steps": steps,
        "wire_rpcs": snap.get("wire_rpc", 0),
        "wire_tx_bytes": snap.get("wire_tx_bytes", 0),
        "wire_rx_bytes": snap.get("wire_rx_bytes", 0),
        "d2h_bytes": snap.get("d2h_bytes", 0),
        "wire_bytes_saved": snap.get("wire_bytes_saved", 0),
        "fused_frames": snap.get("fused_frames", 0),
        "fused_keys": snap.get("fused_keys", 0),
        "compression_auto_off": pre_auto + snap.get("compression_auto_off", 0),
        "step_ms_mean": 1e3 * sum(lat) / len(lat),
        "step_ms_p50": 1e3 * slat[len(slat) // 2],
        "step_ms_max": 1e3 * slat[-1],
        "tail_step_ms_mean": 1e3 * sum(tail) / len(tail),
        "_final": final,
    }


def lossless_report() -> dict:
    """Wire lossless container on representative control-plane bodies
    (the op-24/25 class BYTEPS_WIRE_LOSSLESS frames): per-body ratio,
    C-vs-pure bit parity, and throughput of both implementations.  The
    MIGRATE body carries the state a reshard actually moves — JSON-ish
    rank tables plus a zero-heavy fp32 store block shaped like fresh
    Adam second-moments; RESYNC carries a wide per-key status table."""
    from byteps_tpu.common.types import DataType
    from byteps_tpu.comm.transport import (
        encode_migrate_state,
        encode_resync_state,
    )
    from byteps_tpu.compression import lossless as lz

    rng = np.random.default_rng(7)
    store = rng.standard_normal(8192).astype(np.float32)
    store[rng.random(8192) < 0.7] = 0.0  # sparse-updated slot block
    meta = {
        "key": 7, "epoch": 3, "dtype": int(DataType.FLOAT32),
        "store_version": 40, "recv_count": 0,
        "push_seen": {str(r): 40 for r in range(8)},
        "init_done": {str(r): 99 for r in range(8)},
        "compressor_kwargs": {}, "store_nbytes": store.nbytes,
        "accum_nbytes": store.nbytes,
    }
    bodies = {
        "migrate_state": encode_migrate_state(
            meta, store.tobytes(), b"\x00" * store.nbytes),
        "resync_state": encode_resync_state({
            k: {"store_version": 40, "seen": 39, "recv_count": 1,
                "init": True}
            for k in range(256)
        }),
    }
    out = {}
    for name, raw in bodies.items():
        blob = lz.compress_frame(raw)
        assert lz.decompress_frame(blob) == raw
        # pure-python pass: parity + the no-native throughput floor
        saved = lz._native
        try:
            lz._native = False
            pure = lz.compress_frame(raw)
            t0 = time.perf_counter()
            lz.compress_frame(raw)
            py_comp_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            lz.decompress_frame(blob)
            py_deco_s = time.perf_counter() - t0
        finally:
            lz._native = saved
        t0 = time.perf_counter()
        lz.compress_frame(raw)
        c_comp_s = time.perf_counter() - t0
        mb = len(raw) / 1e6
        out[name] = {
            "raw_bytes": len(raw),
            "container_bytes": len(blob),
            "ratio": len(raw) / len(blob),
            "native_parity": pure == blob,
            "native_available": bool(lz._native),
            "compress_mbps_native": mb / max(1e-9, c_comp_s),
            "compress_mbps_pure": mb / max(1e-9, py_comp_s),
            "decompress_mbps_pure": mb / max(1e-9, py_deco_s),
        }
    return out


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--keys", type=int, default=48)
    ap.add_argument("--bytes", type=int, default=16384)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--threshold", type=int, default=16384)
    ap.add_argument("--rate-mbps", type=float, default=200.0,
                    help="shaped-link bandwidth (the bandwidth-bound "
                         "config the compressed path is for)")
    ap.add_argument("--delay-ms", type=float, default=0.2)
    ap.add_argument("--engine", choices=("python", "native"),
                    default="python")
    ap.add_argument("--skip-auto", action="store_true")
    ap.add_argument("--out", default="COMPRESS_BENCH_r08.json")
    args = ap.parse_args()

    def mode(codec, threshold, auto=False, jax_in=False):
        return run_mode(codec, threshold, args.keys, args.bytes, args.steps,
                        args.rate_mbps, args.delay_ms, args.engine, auto,
                        jax_in)

    rows = {}
    for codec in ("", "onebit", "topk"):
        name = codec or "raw"
        rows[f"{name}_unfused"] = mode(codec, 0)
        rows[f"{name}_fused"] = mode(codec, args.threshold)
    # device axis: raw-with-jax-inputs is the measured D2H baseline the
    # device rows are judged against (host staging of the full fp32)
    rows["raw_jax_fused"] = mode("", args.threshold, jax_in=True)
    rows["topk_device_unfused"] = mode("topk_bare", 0, jax_in=True)
    rows["topk_device_fused"] = mode("topk_bare", args.threshold,
                                     jax_in=True)
    if not args.skip_auto:
        rows["auto"] = mode("topk_full", args.threshold, auto=True)

    # compressed-fused vs compressed-unfused must be BITWISE identical —
    # same codec math, different framing (raw pair checked the same way;
    # the device pair pins the device packer across framings too)
    for name in ("raw", "onebit", "topk", "topk_device"):
        a, b = rows[f"{name}_unfused"], rows[f"{name}_fused"]
        for nm, ref in a["_final"].items():
            np.testing.assert_array_equal(
                b["_final"][nm], ref,
                err_msg=f"{name}: fused vs unfused results diverged ({nm})",
            )
    for r in rows.values():
        r.pop("_final")

    raw_f, ob_u, ob_f = rows["raw_fused"], rows["onebit_unfused"], rows["onebit_fused"]
    raw_jax, dev_f = rows["raw_jax_fused"], rows["topk_device_fused"]
    report = {
        "workload": {
            "keys": args.keys, "bytes_per_key": args.bytes,
            "steps": args.steps, "threshold": args.threshold,
            "rate_mbps": args.rate_mbps, "delay_ms": args.delay_ms,
            "engine": args.engine,
        },
        "headline": {
            # the three-way composition win (ISSUE 11 acceptance)
            "rpc_reduction_vs_compressed_unfused":
                ob_u["wire_rpcs"] / max(1, ob_f["wire_rpcs"]),
            "bytes_reduction_vs_raw_fused":
                raw_f["wire_tx_bytes"] / max(1, ob_f["wire_tx_bytes"]),
            "speedup_vs_raw_fused":
                raw_f["step_ms_mean"] / ob_f["step_ms_mean"],
            "speedup_vs_compressed_unfused":
                ob_u["step_ms_mean"] / ob_f["step_ms_mean"],
            "bitwise_identical_fused_vs_unfused": True,
            # device path: what actually crossed the D2H boundary, vs
            # the raw jax lane's full-fp32 staging and vs what hit the
            # wire (docs/gradient-compression.md "Device path")
            "device_d2h_reduction_vs_raw_jax":
                raw_jax["d2h_bytes"] / max(1, dev_f["d2h_bytes"]),
            "device_d2h_to_wire_tx_ratio":
                dev_f["d2h_bytes"] / max(1, dev_f["wire_tx_bytes"]),
            "device_step_vs_host_compressed_fused":
                dev_f["step_ms_mean"]
                / max(1e-9, rows["topk_fused"]["step_ms_mean"]),
        },
        "acceptance": {},
        **rows,
    }
    if "auto" in rows:
        report["headline"]["auto_disabled_keys"] = rows["auto"][
            "compression_auto_off"
        ]
        # post-settle steps should run near raw-fused speed (the codec
        # is off for every key by then)
        report["headline"]["auto_tail_vs_raw_fused"] = (
            rows["auto"]["tail_step_ms_mean"]
            / max(1e-9, raw_f["tail_step_ms_mean"])
        )
    report["acceptance"] = {
        "compressed_fused_fewer_rpcs_than_compressed_unfused":
            ob_f["wire_rpcs"] < ob_u["wire_rpcs"],
        "compressed_fused_fewer_bytes_than_raw_fused":
            ob_f["wire_tx_bytes"] < raw_f["wire_tx_bytes"],
        "compressed_fused_faster_than_raw_fused":
            ob_f["step_ms_mean"] < raw_f["step_ms_mean"],
        "compressed_fused_faster_than_compressed_unfused":
            ob_f["step_ms_mean"] < ob_u["step_ms_mean"],
        "auto_policy_disabled_all_keys":
            ("auto" not in rows
             or rows["auto"]["compression_auto_off"] == args.keys),
        # the device-path claim: only wire-sized bytes cross D2H — the
        # copy stage never staged a raw fp32 gradient on these lanes
        "device_d2h_no_more_than_wire_tx":
            dev_f["d2h_bytes"] <= dev_f["wire_tx_bytes"],
        "device_d2h_far_below_raw_staging":
            dev_f["d2h_bytes"] * 4 < raw_jax["d2h_bytes"],
        # same-input A/B on the shaped link: both lanes take jax
        # arrays, one packs on device and ships wire bytes, the other
        # stages raw fp32 and ships it all
        "device_fused_faster_than_raw_jax_fused":
            dev_f["step_ms_mean"] < raw_jax["step_ms_mean"],
    }
    report["note_device_step_time"] = (
        "device_step_vs_host_compressed_fused is reported, not gated: "
        "on this CPU harness the 'device' packer is jax-on-CPU, so its "
        "per-key dispatch overhead is an emulation artifact — the D2H "
        "byte counts (the quantity the device path exists for) are "
        "exact either way"
    )
    report["lossless"] = lossless_report()
    report["acceptance"]["lossless_ratio_at_least_1_3"] = all(
        r["ratio"] >= 1.3 for r in report["lossless"].values()
    )
    report["acceptance"]["lossless_native_bit_parity"] = all(
        r["native_parity"] for r in report["lossless"].values()
    )

    # one artifact carries both engines: python rows own the top level,
    # a native rerun lands under "native" (fusion_bench.py convention)
    existing = {}
    if os.path.exists(args.out):
        try:
            with open(args.out) as f:
                existing = json.load(f)
        except (ValueError, OSError):
            existing = {}
    if args.engine == "native":
        merged = existing or {}
        merged["native"] = report
        merged["native"]["note"] = (
            "native response direction is unshaped under the rate/delay "
            "knobs — within-engine ratios are fair, cross-engine "
            "latency is not comparable"
        )
        report = merged
    else:
        if "native" in existing:
            report["native"] = existing["native"]
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2)
    print(json.dumps(report, indent=2))


if __name__ == "__main__":
    main()
