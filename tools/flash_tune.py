"""On-chip flash-attention block-size sweep vs the dense reference.

Times fwd+bwd (value_and_grad of a sum-of-squares) for the Pallas flash
kernel across (block_q, block_k) candidates and sequence lengths, against
XLA's fused dense attention — the data behind TransformerConfig.use_flash
defaults.  Refuses to run off-TPU (CPU timings say nothing about Mosaic).

    python tools/flash_tune.py [--seqs 512,1024,2048,4096] [--bh 8,4]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seqs", default="512,1024,2048,4096")
    ap.add_argument("--bh", default="8,4",
                    help="batch,heads used at every seq")
    ap.add_argument("--dh", type=int, default=64)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--no-write", action="store_true",
                    help="don't persist winners to ops/flash_blocks.json")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    if jax.devices()[0].platform != "tpu":
        print("not on TPU — refusing (flash timings need real Mosaic)")
        return 2

    from byteps_tpu.ops.flash_attention import flash_attention, _dense_reference

    b, h = (int(x) for x in args.bh.split(","))
    dh = args.dh
    blocks = [128, 256, 512]

    def time_fn(fn, *xs):
        f = jax.jit(jax.value_and_grad(lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)))
        out = f(*xs)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(args.steps):
            out = f(*xs)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / args.steps * 1e3  # ms

    rng = np.random.default_rng(0)
    winners = {}   # seq -> {blocks, flash_ms, dense_ms}
    for s in (int(x) for x in args.seqs.split(",")):
        q, k, v = (
            jnp.asarray(rng.normal(size=(b, h, s, dh)).astype(np.float32) * 0.1,
                        jnp.bfloat16)
            for _ in range(3)
        )
        try:
            dense_ms = time_fn(
                lambda q, k, v: _dense_reference(q, k, v, True, dh ** -0.5), q, k, v
            )
        except Exception as e:  # noqa: BLE001 (dense S^2 can OOM at long S)
            dense_ms = None
            print(f"seq {s}: dense failed ({type(e).__name__})")
        best = None
        for bq in blocks:
            for bk in blocks:
                if s % bq or s % bk:
                    continue
                try:
                    ms = time_fn(
                        lambda q, k, v, bq=bq, bk=bk: flash_attention(
                            q, k, v, causal=True, block_q=bq, block_k=bk
                        ),
                        q, k, v,
                    )
                except Exception as e:  # noqa: BLE001
                    print(f"seq {s} flash bq={bq} bk={bk}: {type(e).__name__}")
                    continue
                tag = ""
                if best is None or ms < best[0]:
                    best = (ms, bq, bk)
                    tag = " *"
                print(f"seq {s} flash bq={bq} bk={bk}: {ms:8.2f} ms{tag}")
        if dense_ms is not None:
            print(f"seq {s} dense:               {dense_ms:8.2f} ms")
        if best is not None:
            winners[s] = {
                "blocks": [best[1], best[2]],
                "flash_ms": round(best[0], 3),
                "dense_ms": None if dense_ms is None else round(dense_ms, 3),
            }
        if best is not None and dense_ms is not None:
            verdict = "flash WINS" if best[0] < dense_ms else "dense wins"
            print(
                f"seq {s}: best flash {best[0]:.2f} ms (bq={best[1]}, "
                f"bk={best[2]}) vs dense {dense_ms:.2f} ms → {verdict}"
            )
    if winners and not args.no_write:
        # persist so the kernels' tuned_blocks() table picks the winners
        # up on the next run (bench.py reruns follow in the chip watcher)
        import importlib
        import json

        # ops/__init__ re-exports the flash_attention FUNCTION, which
        # shadows the submodule in from-import; resolve the module itself
        _fa_mod = importlib.import_module("byteps_tpu.ops.flash_attention")
        path = _fa_mod._TUNED_PATH  # producer/consumer share one location
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            doc = {}
        blocks = doc.get("blocks", {})
        meta = doc.get("meta", {})
        for s, w in winners.items():
            blocks[str(s)] = w["blocks"]
            meta[str(s)] = {
                "flash_ms": w["flash_ms"], "dense_ms": w["dense_ms"],
                "bh": args.bh, "dh": args.dh,
            }
        with open(path, "w") as f:
            json.dump({"blocks": blocks, "meta": meta}, f, indent=1)
        print(f"wrote {len(winners)} tuned block entries -> {path}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
