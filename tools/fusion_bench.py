"""Small-tensor fusion microbenchmark — fused vs. unfused RPC count and
step latency on a many-small-keys workload.

The workload the FUSE stage exists for: N small tensors (default 512 ×
4 KB — the bias/layernorm population of a transformer) pushed+pulled per
step through a live in-process PS cluster.  Unfused, every key pays its
own framed push RPC and pull RPC (2N wire messages per step, each with
its own deadline arm and retry state); fused, same-server neighbors ride
multi-key Op.FUSED frames.

    python tools/fusion_bench.py [--keys 512] [--bytes 4096] [--steps 10]
                                 [--threshold 16384] [--delay-ms 0.1]
                                 [--rate-mbps 0] [--chaos]
                                 [--engine python|native]
                                 [--out FUSION_BENCH.json]

Runs the SAME deterministic workload twice — BYTEPS_FUSION_THRESHOLD=0
(off) then =<threshold> — asserts the pull results are bitwise identical
across modes, and writes a JSON artifact with per-mode wire_rpc counts
and step-latency stats plus the fused/unfused ratios.  ``--chaos`` adds
a third+fourth run under the deterministic chaos schedule (fixed seed,
5% frame drops) and asserts bitwise equality there too.

``--engine native`` runs the A/B against the GIL-free C++ server engine
(BYTEPS_SERVER_NATIVE=1 — protocol-complete since the native-parity
port, Op.FUSED included) and merges its rows under a top-level
``"native"`` key of the SAME artifact, so FUSION_BENCH.json carries the
Python/native A/B side by side.

Acceptance (ISSUE 2): rpc_reduction ≥ 2× and speedup ≥ 1.3× on the
default workload.  (ISSUE 5): the native-engine fused run matches the
Python engine's wire-RPC reduction and is ≥ its fused throughput.
"""

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np


def _reset_runtime() -> None:
    """Tear down the process-global worker runtime between modes."""
    from byteps_tpu.common import config as _config
    from byteps_tpu.common import registry as _registry
    from byteps_tpu.core import state as _state

    _state.shutdown_state()
    _registry.reset_registry()
    _config.clear_config()


def run_mode(threshold: int, keys: int, nbytes: int, steps: int,
             delay_ms: float, rate_mbps: float, chaos: bool,
             engine: str = "python") -> dict:
    """One full cluster bring-up → timed steps → teardown; returns stats
    plus the final step's results for cross-mode bitwise comparison."""
    from byteps_tpu.common.config import Config
    from byteps_tpu.comm.rendezvous import Scheduler
    from byteps_tpu.core.telemetry import counters
    from byteps_tpu.server.server import NativePSServer, PSServer

    os.environ["BYTEPS_FUSION_THRESHOLD"] = str(threshold)
    os.environ["BYTEPS_FUSION_CYCLE_MS"] = "2"
    os.environ["BYTEPS_VAN_DELAY_MS"] = str(delay_ms)
    os.environ["BYTEPS_VAN_RATE_MBYTES_S"] = str(rate_mbps)
    if chaos:
        os.environ.update({
            "BYTEPS_VAN": "chaos:tcp",
            "BYTEPS_CHAOS_SEED": "1234",
            "BYTEPS_CHAOS_DROP": "0.02",
            "BYTEPS_RPC_DEADLINE_S": "0.5",
            "BYTEPS_INIT_DEADLINE_S": "1.0",
            "BYTEPS_RPC_RETRIES": "8",
            "BYTEPS_RPC_BACKOFF_S": "0.05",
            "BYTEPS_CONNECT_RETRY_S": "0.3",
            "BYTEPS_DEGRADED_STEP_RETRIES": "3",
        })
    else:
        os.environ["BYTEPS_VAN"] = "tcp"

    sched = Scheduler(num_workers=1, num_servers=1, host="127.0.0.1")
    sched.start()
    os.environ.update({
        "DMLC_PS_ROOT_URI": "127.0.0.1",
        "DMLC_PS_ROOT_PORT": str(sched.port),
        "DMLC_NUM_WORKER": "1",
        "DMLC_NUM_SERVER": "1",
        "BYTEPS_FORCE_DISTRIBUTED": "1",
    })
    if engine == "native":
        # GIL-free C++ data plane (protocol-complete: Op.FUSED, the
        # exactly-once ledger, RESYNC).  Note: with link shaping on
        # (--delay-ms/--rate-mbps) the native engine's RESPONSE direction
        # bypasses the shaper — the within-engine A/B stays fair, the
        # cross-engine latency comparison carries that caveat.
        os.environ["BYTEPS_SERVER_NATIVE"] = "1"
        srv = NativePSServer(Config.from_env())
    else:
        os.environ.pop("BYTEPS_SERVER_NATIVE", None)
        srv = PSServer(Config.from_env())
    threading.Thread(target=srv.start, daemon=True).start()

    import byteps_tpu as bps

    n = max(1, nbytes // 4)
    rng = np.random.default_rng(42)
    base = [rng.standard_normal(n).astype(np.float32) for _ in range(keys)]
    names = [f"fb.{i}" for i in range(keys)]
    final = {}
    try:
        bps.init()
        # warmup step: init barriers + first-round allocation (unfuseable,
        # excluded from timing)
        hs = [bps.push_pull_async(x, name=nm, average=False)
              for nm, x in zip(names, base)]
        for h in hs:
            bps.synchronize(h)
        counters().reset()
        lat = []
        for step in range(steps):
            scale = np.float32(step + 2)
            t0 = time.perf_counter()
            hs = [bps.push_pull_async(x * scale, name=nm, average=False)
                  for nm, x in zip(names, base)]
            outs = [np.asarray(bps.synchronize(h)) for h in hs]
            lat.append(time.perf_counter() - t0)
            for x, out in zip(base, outs):
                np.testing.assert_array_equal(out, x * scale)
            if step == steps - 1:
                final = {nm: out for nm, out in zip(names, outs)}
        snap = counters().snapshot()
    finally:
        bps.shutdown()
        _reset_runtime()
        srv.stop()
        sched.stop()
    lat.sort()
    return {
        "engine": engine,
        "threshold": threshold,
        "chaos": chaos,
        "steps": steps,
        "wire_rpcs": snap.get("wire_rpc", 0),
        "wire_rpcs_per_step": snap.get("wire_rpc", 0) / steps,
        "fused_frames": snap.get("fused_frames", 0),
        "fused_keys": snap.get("fused_keys", 0),
        # C++-engine-side confirmation (0 under the Python engine): the
        # frames were actually unpacked by the GIL-free data plane
        "native_fused_frames": snap.get("native_fused_frames", 0),
        "rpc_retry": snap.get("rpc_retry", 0),
        "flush_full": snap.get("fusion_flush_full", 0),
        "flush_idle": snap.get("fusion_flush_idle", 0),
        "flush_cycle": snap.get("fusion_flush_cycle", 0),
        "step_ms_mean": 1e3 * sum(lat) / len(lat),
        "step_ms_p50": 1e3 * lat[len(lat) // 2],
        "step_ms_max": 1e3 * lat[-1],
        "steps_per_s": len(lat) / sum(lat),
        "_final": final,
    }


def compare(off: dict, on: dict) -> dict:
    """Bitwise-compare final-step pulls and compute the headline ratios."""
    for nm, ref in off["_final"].items():
        np.testing.assert_array_equal(
            on["_final"][nm], ref,
            err_msg=f"fused vs unfused results diverged for {nm}",
        )
    return {
        "rpc_reduction": off["wire_rpcs"] / max(1, on["wire_rpcs"]),
        "speedup": off["step_ms_mean"] / on["step_ms_mean"],
        "bitwise_identical": True,
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--keys", type=int, default=512)
    ap.add_argument("--bytes", type=int, default=4096)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--threshold", type=int, default=16384)
    ap.add_argument("--delay-ms", type=float, default=0.1,
                    help="shaped-link one-way delay per message")
    ap.add_argument("--rate-mbps", type=float, default=0.0,
                    help="shaped-link bandwidth (0 = unlimited)")
    ap.add_argument("--chaos", action="store_true",
                    help="also compare under the deterministic chaos schedule")
    ap.add_argument("--engine", choices=("python", "native"),
                    default="python",
                    help="server engine for the A/B (native = the "
                         "GIL-free C++ data plane, BYTEPS_SERVER_NATIVE=1)")
    ap.add_argument("--out", default="FUSION_BENCH.json")
    args = ap.parse_args()

    modes = {}
    modes["unfused"] = run_mode(0, args.keys, args.bytes, args.steps,
                                args.delay_ms, args.rate_mbps, False,
                                args.engine)
    modes["fused"] = run_mode(args.threshold, args.keys, args.bytes,
                              args.steps, args.delay_ms, args.rate_mbps,
                              False, args.engine)
    report = {
        "workload": {
            "keys": args.keys, "bytes_per_key": args.bytes,
            "steps": args.steps, "threshold": args.threshold,
            "delay_ms": args.delay_ms, "rate_mbps": args.rate_mbps,
            "engine": args.engine,
        },
        "clean": compare(modes["unfused"], modes["fused"]),
    }
    if args.chaos:
        modes["unfused_chaos"] = run_mode(0, args.keys, args.bytes,
                                          args.steps, args.delay_ms,
                                          args.rate_mbps, True, args.engine)
        modes["fused_chaos"] = run_mode(args.threshold, args.keys,
                                        args.bytes, args.steps,
                                        args.delay_ms, args.rate_mbps, True,
                                        args.engine)
        report["chaos"] = compare(modes["unfused_chaos"],
                                  modes["fused_chaos"])
    for name, m in modes.items():
        m.pop("_final")
        report[name] = m
    report["acceptance"] = {
        "rpc_reduction_ge_2x": report["clean"]["rpc_reduction"] >= 2.0,
        "speedup_ge_1_3x": report["clean"]["speedup"] >= 1.3,
    }

    # The artifact carries BOTH engines' A/B: a python-engine run owns
    # the top level (preserving any existing "native" row), a
    # native-engine run lands under "native" (preserving the top level)
    # with a cross-engine comparison against the python rows.
    existing = {}
    if os.path.exists(args.out):
        try:
            with open(args.out) as f:
                existing = json.load(f)
        except (ValueError, OSError):
            existing = {}

    def same_workload(a: dict, b: dict) -> bool:
        """Cross-engine ratios are only meaningful on the SAME workload
        — compare everything but the engine field."""
        strip = lambda w: {k: v for k, v in (w or {}).items() if k != "engine"}
        return strip(a) == strip(b)

    if args.engine == "native":
        merged = existing or {}
        merged["native"] = report
        if ("fused" in merged and "clean" in merged
                and same_workload(merged.get("workload"),
                                  report["workload"])):
            py_fused = merged["fused"]
            merged["native"]["vs_python"] = {
                "rpc_reduction_matches": bool(
                    report["clean"]["rpc_reduction"]
                    >= 0.9 * merged["clean"]["rpc_reduction"]
                ),
                "fused_steps_per_s_ratio": (
                    report["fused"]["steps_per_s"]
                    / max(1e-9, py_fused["steps_per_s"])
                ),
                # with link shaping on, native responses bypass the
                # shaper — the latency edge includes ~delay_ms per pull
                "note": "native response direction is unshaped under "
                        "--delay-ms/--rate-mbps",
            }
        report = merged
    else:
        if "native" in existing:
            native = dict(existing["native"])
            # the top-level python rows this block's vs_python cited are
            # being replaced — keep the ratios only if this rerun used
            # the identical workload, else they'd cite numbers no longer
            # in the file
            if not same_workload(native.get("workload"),
                                 report["workload"]):
                native.pop("vs_python", None)
            report["native"] = native
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2)
    print(json.dumps(report, indent=2))


if __name__ == "__main__":
    main()
