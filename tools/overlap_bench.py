"""The scheduling-overlap benchmark: proves the OSDI'20 core claim
end-to-end on a latency/bandwidth-shaped fake cluster.

BytePS's headline idea is priority-scheduled communication overlapping
backprop and the NEXT step's forward (reference
scheduled_queue.cc:82-102, priority = −declaration order in
mxnet/__init__.py:52-74; docs/rationale.md's DCN regime).  This tool
measures actual wall-clock training step time of a real torch model
through the real PS plane (in-process scheduler + 2 Python servers +
this worker) over the shaped van (comm/shaping.py), ablating the three
mechanisms the reference stacks:

  full       priority scheduling + cross-barrier + tensor partitioning
  fifo       BYTEPS_SCHEDULING=fifo (arrival order — scheduling off)
  nobarrier  priority + partitioning, but a full gradient barrier every
             step (plain DistributedOptimizer semantics)
  nopart     priority + cross-barrier, partitioning effectively off
             (partition_bytes > largest tensor)

Expected ordering (the claim under test): full is fastest; each
ablation costs wall-clock.  The model is a uniform MLP — bytes and
compute spread evenly across layers (see build_model for why a
concentrated byte mass makes order provably irrelevant): FIFO delivers
the front layer's gradient LAST, so the next forward stalls on the
whole drain and then computes with the wire idle; priority delivers
front-to-back and the forward walks the stream, its compute hidden
inside the inter-arrival gaps.

Run:  python tools/overlap_bench.py [--quick] [--out OVERLAP.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the image presets JAX_PLATFORMS=axon (the tunneled chip); this bench is
# host-side only and must not touch the accelerator — force CPU both ways
# (env alone does not stick once jax is imported)
os.environ["JAX_PLATFORMS"] = "cpu"
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np


def build_model(depth: int, width: int, seed: int = 0):
    """Uniform MLP: equal bytes AND compute per layer.

    The scheduling win is delivery order matching consumption order so
    every inter-arrival gap fills with compute.  That requires the byte
    mass SPREAD across layers — with one dominant tensor (a VGG-style
    fc), forward just waits for that single mass and order cannot
    matter; we measured exactly that (r5 probe).  A uniform stack is
    also the regime the OSDI'20 analysis models: per-layer wire time >
    per-layer backward time (a backlog forms) and ≥ per-layer forward
    time (the stream gates the forward walk).  The win then approaches
    (L−1)·f_layer — every front layer's forward hidden inside the
    drain, which FIFO (reverse order) exposes in full."""
    import torch

    torch.manual_seed(seed)
    torch.set_num_threads(1)  # the bench box has one core; be honest about it
    layers = []
    for _ in range(depth):
        layers += [torch.nn.Linear(width, width), torch.nn.ReLU()]
    layers.append(torch.nn.Linear(width, 10))
    return torch.nn.Sequential(*layers)


def run_config(name: str, env: dict, *, barrier_each_step: bool,
               depth: int, width: int, batch: int,
               steps: int, warmup: int) -> dict:
    """One fresh fake cluster + one training run; returns timings."""
    import torch

    from byteps_tpu.common.config import Config
    from byteps_tpu.comm.rendezvous import Scheduler
    from byteps_tpu.server.server import PSServer

    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    sched = Scheduler(num_workers=1, num_servers=2, host="127.0.0.1")
    sched.start()
    os.environ["DMLC_PS_ROOT_URI"] = "127.0.0.1"
    os.environ["DMLC_PS_ROOT_PORT"] = str(sched.port)
    os.environ["DMLC_NUM_WORKER"] = "1"
    os.environ["DMLC_NUM_SERVER"] = "2"
    os.environ["BYTEPS_FORCE_DISTRIBUTED"] = "1"
    servers = [PSServer(Config.from_env()) for _ in range(2)]
    for srv in servers:
        threading.Thread(target=srv.start, daemon=True).start()

    import byteps_tpu as bps
    from byteps_tpu.torch.cross_barrier import CrossBarrier

    bps.init()
    model = build_model(depth, width)
    opt = CrossBarrier(model, "sgd", lr=0.05)
    g = torch.Generator().manual_seed(42)
    x = torch.randn(batch, width, generator=g)
    y = 0.1 * torch.randn(batch, 10, generator=g)

    times, losses = [], []
    for step in range(warmup + steps):
        t0 = time.monotonic()
        loss = torch.nn.functional.mse_loss(model(x), y)
        opt.zero_grad()
        loss.backward()
        if barrier_each_step:
            opt.step()  # plain-optimizer semantics: wait everything now
        dt = time.monotonic() - t0
        losses.append(float(loss.detach()))
        if step >= warmup:
            times.append(dt)
    opt.step()  # final barrier so shutdown never strands handles
    bps.shutdown()
    for srv in servers:
        srv.stop()
    sched.stop()
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v

    times.sort()
    return {
        "grad_bytes": sum(4 * p.numel() for p in model.parameters()),
        "median_step_s": times[len(times) // 2],
        "mean_step_s": sum(times) / len(times),
        "steps": times,
        "loss_first": losses[0],
        "loss_last": losses[-1],
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="scaled-down run for the test suite")
    ap.add_argument("--out", default="")
    ap.add_argument("--rate-mbps", type=float, default=4.0)
    ap.add_argument("--delay-ms", type=float, default=1.0)
    ap.add_argument("--trials", type=int, default=3,
                    help="interleaved round-robin trials per config — "
                    "background load on the shared 1-core box then hits "
                    "every config equally instead of whichever ran last")
    args = ap.parse_args()

    if args.quick:
        # small but with REAL forward compute: the priority-vs-fifo win is
        # exactly the forward time hidden into the wire drain, so a
        # compute-free model would (correctly) show no difference
        dims = dict(depth=6, width=256, batch=1024)
        steps, warmup = 4, 1
        part = str(64 << 10)
        trials = 1
    else:
        # calibrated on this box (quiet, torch ~130 GF/s single-thread):
        # f ≈ 35ms/layer fwd, c ≈ 70ms/layer bwd, w = 1MB/(2×4MB/s)
        # = 125ms/layer — the w > c > f regime where delivery order can
        # hide the forward walk; 64KB partitions keep the preemption
        # quantum (in-flight blocking) small so a jumped front-layer
        # key's round trip isn't eaten by per-message latency
        dims = dict(depth=16, width=512, batch=8192)
        steps, warmup = 6, 2
        part = str(64 << 10)
        trials = max(1, args.trials)

    shaped = {
        "BYTEPS_VAN_DELAY_MS": str(args.delay_ms),
        "BYTEPS_VAN_RATE_MBYTES_S": str(args.rate_mbps),
        "BYTEPS_VAN_SHAPE_BUF_KB": "64",
    }
    nopart_bytes = str(64 << 20)  # larger than any tensor: partitioning off

    configs = {
        "full": (
            {**shaped, "BYTEPS_SCHEDULING": "priority",
             "BYTEPS_PARTITION_BYTES": part},
            dict(barrier_each_step=False),
        ),
        "fifo": (
            {**shaped, "BYTEPS_SCHEDULING": "fifo",
             "BYTEPS_PARTITION_BYTES": part},
            dict(barrier_each_step=False),
        ),
        "nobarrier": (
            {**shaped, "BYTEPS_SCHEDULING": "priority",
             "BYTEPS_PARTITION_BYTES": part},
            dict(barrier_each_step=True),
        ),
        "nopart": (
            {**shaped, "BYTEPS_SCHEDULING": "priority",
             "BYTEPS_PARTITION_BYTES": nopart_bytes},
            dict(barrier_each_step=False),
        ),
        # every mechanism off at once — what a naive PS worker would do;
        # full vs none is the compounded value of the whole OSDI stack
        "none": (
            {**shaped, "BYTEPS_SCHEDULING": "fifo",
             "BYTEPS_PARTITION_BYTES": nopart_bytes},
            dict(barrier_each_step=True),
        ),
    }

    all_steps = {name: [] for name in configs}
    losses = {}
    for trial in range(trials):
        for name, (env, kw) in configs.items():
            print(f"[overlap_bench] trial {trial}: {name} ...", file=sys.stderr)
            r = run_config(name, env, **kw, **dims, steps=steps, warmup=warmup)
            all_steps[name].extend(r["steps"])
            losses[name] = (r["loss_first"], r["loss_last"])
            grad_bytes = r["grad_bytes"]
            print(
                f"[overlap_bench] trial {trial}: {name} median "
                f"{r['median_step_s']*1e3:.1f} ms/step",
                file=sys.stderr,
            )
    results = {}
    for name, ts in all_steps.items():
        ts = sorted(ts)
        results[name] = {
            "median_step_s": ts[len(ts) // 2],
            "mean_step_s": sum(ts) / len(ts),
            "steps": ts,
            "loss_first": losses[name][0],
            "loss_last": losses[name][1],
        }

    med = {k: v["median_step_s"] for k, v in results.items()}
    verdicts = {
        "priority_beats_fifo": med["full"] < med["fifo"],
        "crossbarrier_beats_barrier": med["full"] < med["nobarrier"],
        "partitioning_beats_nopart": med["full"] < med["nopart"],
        "full_stack_beats_none": med["full"] < med["none"],
    }
    out = {
        "what": "wall-clock training step time, shaped fake cluster "
                "(2 servers), torch MLP via torch CrossBarrier; "
                "ablations of the OSDI'20 scheduling stack",
        "shaping": {"rate_mbps": args.rate_mbps, "delay_ms": args.delay_ms,
                    "buf_kb": 64},
        "model": {"arch": "uniform-mlp", **dims},
        "grad_bytes": grad_bytes,
        "configs": results,
        "median_step_s": med,
        "speedup_vs_fifo": med["fifo"] / med["full"],
        "speedup_vs_nobarrier": med["nobarrier"] / med["full"],
        "speedup_vs_nopart": med["nopart"] / med["full"],
        "speedup_vs_none": med["none"] / med["full"],
        "verdicts": verdicts,
    }
    line = json.dumps(out)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    print(line)


if __name__ == "__main__":
    main()
