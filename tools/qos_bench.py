"""Per-tenant QoS A/B: two shaped jobs sharing one PS fleet.

The multi-tenant contract (docs/async.md): a latency-sensitive job's
p99 step time stays flat while a bulk job saturates the rest of the
fleet — IF the operator declares QoS (``BYTEPS_JOB_PRIORITY`` weights
the client/server queues, ``BYTEPS_JOB_QUOTA_MBPS`` meters admission).
This bench measures exactly that claim on a rate-shaped loopback link:

- **solo**:       the latency job alone on 2 servers — its baseline.
- **noqos**:      latency job + a bulk job flooding many in-flight
                  partitions, neither declaring QoS — the bulk backlog
                  sits in front of the latency job's requests on the
                  (single-threaded, shaped) server engine queue.
- **qos**:        same contention, latency job at priority 100, bulk
                  job metered by an admission quota — the server's WFQ
                  lanes + token bucket protect the latency job.

Each phase runs a fresh in-process fleet (scheduler + 2 Python-engine
PSServers) with the two jobs as SUBPROCESS workers (their own
``BYTEPS_JOB_ID`` env — real tenant isolation, not declare-kwarg
emulation).  Output: ``QOS_BENCH_r01.json`` with per-job step-time
p50/p99 per phase and the headline ratios.

    python tools/qos_bench.py --out QOS_BENCH_r01.json
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: shaped link rate (MB/s) — slow enough that a bulk flood visibly
#: queues, fast enough that the bench stays under a minute
RATE_MBYTES_S = 8.0

_WORKER_BODY = r"""
import json, os, sys, time
import numpy as np
os.environ["JAX_PLATFORMS"] = "cpu"
import byteps_tpu as bps

role = os.environ["QOS_BENCH_ROLE"]
steps = int(os.environ["QOS_BENCH_STEPS"])
dim = int(os.environ["QOS_BENCH_DIM"])
delay = float(os.environ.get("QOS_BENCH_WARM_DELAY_S", "0") or 0)
bps.init()
x = np.ones(dim, dtype=np.float32)
times = []
# one warm-up round covers init barriers + first-round allocation
bps.push_pull(x, name=f"qos.{role}", average=False)
if delay > 0:
    # measurement must start INSIDE the contended window: the bulk
    # neighbor's first multi-MB round takes ~1s on the shaped link, so
    # a short latency phase starting right after the bring-up barrier
    # could finish before the flood even arrives
    time.sleep(delay)
for s in range(steps):
    t0 = time.monotonic()
    bps.push_pull(x, name=f"qos.{role}", average=False)
    times.append(time.monotonic() - t0)
# per-tenant SLO surface (docs/async.md): how often the flight
# recorder's slo_breach rule fired, and how many bundles the rate
# limiter actually let through
from byteps_tpu.core.flightrec import get_process_recorder
from byteps_tpu.core.telemetry import counters
labeled = counters().snapshot_labeled().get("flight_trigger", {})
slo_fired = sum(
    v for lkey, v in labeled.items()
    if dict(lkey).get("rule") == "slo_breach"
)
rec = get_process_recorder()
bundles = sum(
    1 for p in (rec.bundles_written if rec is not None else ())
    if "-slo_breach-" in p
)
print("QOS_RESULT " + json.dumps({
    "role": role, "times": times,
    "slo_breach_fired": slo_fired, "bundles": bundles,
}))
sys.stdout.flush()
bps.shutdown()
"""


def _percentile(vals, q):
    """Floor-interpolated percentile: at bench-sized n the p99 is the
    second-worst sample, not the max — one OS scheduling blip must not
    dominate a tail estimate built from tens of samples."""
    vals = sorted(vals)
    if not vals:
        return 0.0
    i = min(len(vals) - 1, int(q * (len(vals) - 1)))
    return vals[i]


def run_phase(name: str, bulk: bool, qos: bool, steps: int = 40,
              bulk_dim: int = 1 << 20, lat_dim: int = 1 << 14,
              lat_priority: int = None, bulk_quota: float = None,
              lat_slo_s: float = 0.0) -> dict:
    """One fleet bring-up + measurement; returns per-job stats."""
    env_base = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "BYTEPS_VAN": "tcp",
        "BYTEPS_VAN_RATE_MBYTES_S": str(RATE_MBYTES_S),
        # one engine thread per server: the shared service point where a
        # bulk backlog can actually sit in front of the latency job
        "BYTEPS_SERVER_ENGINE_THREAD": "1",
        # many in-flight bulk partitions = a real backlog
        "BYTEPS_PARTITION_BYTES": str(256 * 1024),
        # a shaping buffer SMALLER than a bulk reply: every 256KB pull
        # reply genuinely occupies the sender until the wire drains, so
        # the inline-send head-of-line block (the thing QoS's reply
        # writers remove) is deterministic, not a burst-timing lottery
        "BYTEPS_VAN_SHAPE_BUF_KB": "64",
        "BYTEPS_HEARTBEAT_INTERVAL": "1",
        "BYTEPS_FORCE_DISTRIBUTED": "1",
        "DMLC_NUM_WORKER": "2" if bulk else "1",
        "DMLC_NUM_SERVER": "2",
        "DMLC_PS_ROOT_URI": "127.0.0.1",
    }
    env_base.pop("BYTEPS_JOB_ID", None)
    os.environ.update({k: env_base[k] for k in (
        "BYTEPS_VAN", "BYTEPS_VAN_RATE_MBYTES_S",
        "BYTEPS_VAN_SHAPE_BUF_KB",
        "BYTEPS_SERVER_ENGINE_THREAD", "BYTEPS_PARTITION_BYTES",
        "DMLC_NUM_WORKER", "DMLC_NUM_SERVER", "DMLC_PS_ROOT_URI",
    )})

    from byteps_tpu.common.config import Config
    from byteps_tpu.comm.rendezvous import Scheduler
    from byteps_tpu.server.server import PSServer

    sched = Scheduler(num_workers=2 if bulk else 1, num_servers=2,
                      host="127.0.0.1")
    sched.start()
    os.environ["DMLC_PS_ROOT_PORT"] = str(sched.port)
    env_base["DMLC_PS_ROOT_PORT"] = str(sched.port)
    fleet = [PSServer(Config.from_env()) for _ in range(2)]
    for srv in fleet:
        threading.Thread(target=srv.start, daemon=True).start()

    def spawn(role: str, job: int, wsteps: int, dim: int,
              priority: int, quota: float, slo: float = 0.0) -> subprocess.Popen:
        env = dict(env_base)
        env.update({
            "BYTEPS_JOB_ID": str(job),
            "BYTEPS_JOB_PRIORITY": str(priority),
            "BYTEPS_JOB_QUOTA_MBPS": str(quota),
            "BYTEPS_JOB_SLO_S": str(slo),
            "QOS_BENCH_ROLE": role,
            "QOS_BENCH_STEPS": str(wsteps),
            "QOS_BENCH_DIM": str(dim),
            # latency job only: start measuring once the bulk flood is
            # established (every phase gets the same delay so the
            # baselines stay comparable)
            "QOS_BENCH_WARM_DELAY_S": "1.5" if role == "latency" else "0",
        })
        return subprocess.Popen(
            [sys.executable, "-c", _WORKER_BODY], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
            cwd=REPO,
        )

    if lat_priority is None:
        lat_priority = 100 if qos else 1
    if bulk_quota is None:
        bulk_quota = RATE_MBYTES_S / 2 if qos else 0.0
    procs = {
        "latency": spawn("latency", 1, steps, lat_dim,
                         priority=lat_priority, quota=0.0,
                         slo=lat_slo_s),
    }
    if bulk:
        # the bulk job steps "forever" (generous count); it is
        # terminated once the latency job finishes measuring
        procs["bulk"] = spawn(
            "bulk", 2, 10_000, bulk_dim,
            priority=1, quota=bulk_quota,
        )

    results = {}
    try:
        out, _ = procs["latency"].communicate(timeout=600)
        for line in out.splitlines():
            if line.startswith("QOS_RESULT "):
                results["latency"] = json.loads(line[len("QOS_RESULT "):])
        if procs["latency"].returncode != 0:
            raise RuntimeError(f"latency worker failed in phase {name}")
    finally:
        for key, p in procs.items():
            if p.poll() is None:
                p.terminate()
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
        for srv in fleet:
            srv.stop()
        sched.stop()

    if "latency" not in results:
        raise RuntimeError(f"phase {name}: no latency result line")
    times = results["latency"]["times"]
    stats = {
        "steps": len(times),
        "p50_ms": round(_percentile(times, 0.50) * 1e3, 2),
        "p90_ms": round(_percentile(times, 0.90) * 1e3, 2),
        "p99_ms": round(_percentile(times, 0.99) * 1e3, 2),
        "mean_ms": round(statistics.fmean(times) * 1e3, 2),
        "slo_breach_fired": results["latency"].get("slo_breach_fired", 0),
        "slo_bundles": results["latency"].get("bundles", 0),
    }
    print(f"  phase {name:8s}: latency-job p50={stats['p50_ms']}ms "
          f"p99={stats['p99_ms']}ms over {stats['steps']} steps")
    return stats


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--out", default="QOS_BENCH_r01.json")
    args = ap.parse_args()

    print(f"qos_bench: shaped link {RATE_MBYTES_S} MB/s, 2 servers, "
          "1 engine thread")
    solo = run_phase("solo", bulk=False, qos=False, steps=args.steps)
    noqos = run_phase("noqos", bulk=True, qos=False, steps=args.steps)
    qos = run_phase("qos", bulk=True, qos=True, steps=args.steps)

    result = {
        "config": {
            "rate_mbytes_s": RATE_MBYTES_S,
            "servers": 2,
            "engine_threads": 1,
            "latency_job": {"dim": 1 << 14, "priority_qos": 100},
            "bulk_job": {"dim": 1 << 20,
                         "quota_mbps_qos": RATE_MBYTES_S / 2},
            "steps": args.steps,
        },
        "phases": {"solo": solo, "noqos": noqos, "qos": qos},
        "headline": {
            "p99_noqos_over_solo": round(
                noqos["p99_ms"] / max(0.01, solo["p99_ms"]), 2
            ),
            "p99_qos_over_solo": round(
                qos["p99_ms"] / max(0.01, solo["p99_ms"]), 2
            ),
        },
    }
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
        f.write("\n")
    print(json.dumps(result["headline"], indent=2))
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
