#!/bin/bash
# Round-5 scaling matrix (SCALING_r05.json builder). Run with the chip
# watcher PAUSED — the cells are CPU-budget measurements.
set -u
cd "$(dirname "$0")/.."
OUT=${1:-/tmp/scaling_r05_cells.jsonl}
LOG=${OUT%.jsonl}.log
: > "$OUT"
: > "$LOG"
run() {  # run <label> -- args...
  label=$1; shift
  [ "$1" = "--" ] && shift
  echo "[scaling_r05] $label ..." >&2
  # pipefail inside the substitution: rc must be python/timeout's exit
  # status, not tail's (tail exits 0 even when the bench died)
  line=$(set -o pipefail; timeout 500 python tools/scaling_bench.py \
      --multiproc --workers 1,2,4,8 --rounds 8 "$@" 2>>"$LOG" | tail -1)
  rc=$?
  if [ $rc -ne 0 ] || [ -z "$line" ]; then
    # a dead/hung cell must be VISIBLE, never a silent malformed line:
    # the assembler refuses flagged cells and names them
    echo "[scaling_r05] CELL FAILED: $label rc=$rc (stderr in $LOG)" >&2
    echo "{\"label\": \"$label\", \"failed\": true, \"rc\": $rc}" >> "$OUT"
    return
  fi
  echo "{\"label\": \"$label\", \"result\": $line}" >> "$OUT"
}
run native-shm-scaledsrv  -- --native --van shm
run native-shm-2srv       -- --native --van shm --servers 2
run native-tcp-scaledsrv  -- --native --van tcp
run native-tcp-2srv       -- --native --van tcp --servers 2
run python-shm-2srv       -- --van shm --servers 2
run python-tcp-2srv       -- --van tcp --servers 2
# two more samples of the headline cell for a median
run native-shm-2srv-rep2  -- --native --van shm --servers 2
run native-shm-2srv-rep3  -- --native --van shm --servers 2
echo "[scaling_r05] done -> $OUT (stderr: $LOG)" >&2
