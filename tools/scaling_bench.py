"""push_pull scaling-efficiency harness (BASELINE.md north star).

The reference's headline metric is scaling efficiency at many workers
(~90% on 256 GPUs, README.md:38-46).  Real multi-host TPU hardware isn't
available in this environment, so this harness measures the PS plane the
same way the reference's fake-cluster tests do: N in-process workers
drive full push+pull rounds against live servers over loopback, and
efficiency(N) = round_time(1) / round_time(N) — ideal pipelining keeps
the round time flat as workers (and total traffic) grow.

    python tools/scaling_bench.py [--workers 1,2,4,8] [--servers 2]
        [--mbytes 4] [--keys 32] [--rounds 10]

Prints ONE JSON line:
    {"metric": "pushpull_scaling_efficiency_8w", "value": ..., ...}
"""

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np

from byteps_tpu.common.config import Config
from byteps_tpu.comm.ps_client import PSClient
from byteps_tpu.comm.rendezvous import Scheduler
from byteps_tpu.server.server import NativePSServer, PSServer


def run_round(client: PSClient, keys, payloads, version: int) -> None:
    """One synchronous push+pull round over all keys, fully overlapped
    (every push launched async, then every pull) — the engine's pipeline
    shape without the device staging."""
    remaining = threading.Event()
    pend = [len(keys) * 2]
    lock = threading.Lock()

    def done(*_a):
        with lock:
            pend[0] -= 1
            if pend[0] == 0:
                remaining.set()

    for key, payload in zip(keys, payloads):
        client.push(key, payload, 0, version, cb=done)
    for key in keys:
        client.pull(key, version, done)
    # generous: on the 1-core CI/dev box a stray jax-importing process can
    # deschedule every subprocess for tens of seconds at once
    if not remaining.wait(120):
        raise RuntimeError("round timed out")


def measure(n_workers: int, n_servers: int, keys_per_worker: int,
            bytes_per_worker: int, rounds: int, native: bool) -> float:
    """Median per-round wall time with n_workers concurrent clients."""
    sched = Scheduler(num_workers=n_workers, num_servers=n_servers, host="127.0.0.1")
    sched.start()
    os.environ.update({
        "DMLC_PS_ROOT_URI": "127.0.0.1",
        "DMLC_PS_ROOT_PORT": str(sched.port),
        "DMLC_NUM_WORKER": str(n_workers),
        "DMLC_NUM_SERVER": str(n_servers),
        "BYTEPS_FORCE_DISTRIBUTED": "1",
    })
    cfg = Config.from_env()
    servers = [
        (NativePSServer(cfg) if native else PSServer(cfg))
        for _ in range(n_servers)
    ]
    for srv in servers:
        threading.Thread(target=srv.start, daemon=True).start()
    clients = [PSClient(cfg, node_uid=f"w{i}") for i in range(n_workers)]
    ts = [threading.Thread(target=c.connect, daemon=True) for c in clients]
    for t in ts:
        t.start()
    for t in ts:
        t.join(30)

    n_elems = bytes_per_worker // 4 // keys_per_worker
    keys = list(range(keys_per_worker))
    payloads = [np.random.default_rng(k).normal(size=n_elems)
                .astype(np.float32).tobytes() for k in keys]
    init_ts = [
        threading.Thread(
            target=lambda c=c: [c.init_tensor(k, n_elems, 0) for k in keys],
            daemon=True,
        )
        for c in clients
    ]
    for t in init_ts:
        t.start()
    for t in init_ts:
        t.join(30)

    times = []
    errors: list = []
    for r in range(rounds + 2):
        barrier = threading.Barrier(n_workers)

        def worker(c):
            barrier.wait()
            try:
                run_round(c, keys, payloads, r + 1)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        ws = [threading.Thread(target=worker, args=(c,), daemon=True) for c in clients]
        t0 = time.perf_counter()
        for w in ws:
            w.start()
        for w in ws:
            w.join(90)
        # a timed-out or failed round must never be recorded as a sample
        if errors or any(w.is_alive() for w in ws):
            raise RuntimeError(
                f"round {r} failed at {n_workers} workers: "
                f"{errors or 'worker thread hung'}"
            )
        if r >= 2:  # warmup rounds excluded
            times.append(time.perf_counter() - t0)

    for c in clients:
        c.close()
    for srv in servers:
        srv.stop()
    sched.stop()
    return float(np.median(times))


def worker_main(args) -> None:
    """Subprocess worker role (--worker-role): connect, init, run barrier-
    synchronized rounds, print the median barrier-to-barrier round time.
    Timing spans the trailing barrier so every worker reports the GLOBAL
    round time (slowest worker included)."""
    from byteps_tpu.comm.rendezvous import GROUP_WORKERS

    cfg = Config.from_env()
    client = PSClient(cfg)
    client.connect()
    per_worker = int(args.mbytes * 1e6)
    n_elems = per_worker // 4 // args.keys
    keys = list(range(args.keys))
    payloads = [np.random.default_rng(k).normal(size=n_elems)
                .astype(np.float32).tobytes() for k in keys]
    for k in keys:
        client.init_tensor(k, n_elems, 0)
    times = []
    for r in range(args.rounds + 2):
        client.barrier(GROUP_WORKERS)
        t0 = time.perf_counter()
        run_round(client, keys, payloads, r + 1)
        client.barrier(GROUP_WORKERS)
        if r >= 2:  # warmup excluded
            times.append(time.perf_counter() - t0)
    client.close()
    print(json.dumps({"median_round_s": float(np.median(times))}))


def measure_multiproc(n_workers: int, n_servers: int, args) -> float:
    """Median global round time with n_workers worker SUBPROCESSES and
    n_servers server SUBPROCESSES — real parallelism (no shared GIL), the
    honest single-machine proxy for the reference's multi-node topology
    (VERDICT r2 #5; dist_launcher.py:55-120 fan-out, collapsed to one
    host)."""
    import subprocess
    import sys as _sys

    sched = Scheduler(num_workers=n_workers, num_servers=n_servers,
                      host="127.0.0.1")
    sched.start()
    env = {
        **os.environ,
        "DMLC_PS_ROOT_URI": "127.0.0.1",
        "DMLC_PS_ROOT_PORT": str(sched.port),
        "DMLC_NUM_WORKER": str(n_workers),
        "DMLC_NUM_SERVER": str(n_servers),
        "BYTEPS_FORCE_DISTRIBUTED": "1",
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."),
    }
    if args.native:
        env["BYTEPS_SERVER_NATIVE"] = "1"
    srv_env = {**env, "DMLC_ROLE": "server"}
    servers = [
        subprocess.Popen(
            [_sys.executable, "-m", "byteps_tpu.server"],
            env=srv_env, stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
        )
        for _ in range(n_servers)
    ]
    me = os.path.abspath(__file__)
    workers = [
        subprocess.Popen(
            [_sys.executable, me, "--worker-role",
             "--keys", str(args.keys), "--mbytes", str(args.mbytes),
             "--rounds", str(args.rounds)],
            env={**env, "DMLC_ROLE": "worker", "BYTEPS_NODE_UID": f"w{i}",
                 "PYTHONFAULTHANDLER": "1"},
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for i in range(n_workers)
    ]
    outs: list = []
    hung = False
    for w in workers:
        try:
            outs.append(w.communicate(timeout=600)[0])
        except subprocess.TimeoutExpired:
            # dump every live worker's Python stacks (faulthandler on
            # SIGABRT) so a hang leaves a diagnosis, not a bare timeout
            hung = True
            import signal

            for lw in workers[len(outs):]:
                if lw.poll() is None:
                    try:
                        lw.send_signal(signal.SIGABRT)
                    except OSError:
                        pass
            try:
                outs.append(w.communicate(timeout=15)[0])
            except subprocess.TimeoutExpired:
                w.kill()
                outs.append(w.communicate()[0])
    for s in servers:
        s.terminate()
    for s in servers:
        try:
            s.wait(timeout=10)
        except subprocess.TimeoutExpired:
            s.kill()
    sched.stop()
    if hung:
        for lw in workers:
            if lw.poll() is None:
                lw.kill()
        dumps = "\n\n".join(
            f"--- worker {i} ---\n{(out or '')[-3000:]}"
            for i, out in enumerate(outs)
        )
        raise RuntimeError(
            f"scaling round hung at {n_workers} workers; stacks:\n{dumps}"
        )
    medians = []
    for i, (w, out) in enumerate(zip(workers, outs)):
        if w.returncode != 0:
            raise RuntimeError(f"scaling worker {i} failed:\n{out[-2000:]}")
        medians.append(json.loads(out.strip().splitlines()[-1])["median_round_s"])
    return float(max(medians))  # global round = slowest worker's view


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--workers", default="1,2,4,8")
    ap.add_argument("--servers", type=int, default=0,
                    help="server count; 0 = scale with workers (the "
                    "reference's recommended num_servers >= num_workers)")
    ap.add_argument("--mbytes", type=float, default=4.0,
                    help="payload per worker per round (MB)")
    ap.add_argument("--keys", type=int, default=32)
    ap.add_argument("--rounds", type=int, default=10)
    ap.add_argument("--native", action="store_true",
                    help="use the C++ server data plane")
    ap.add_argument("--stripes", type=int, default=0,
                    help="BYTEPS_SERVER_STRIPES for the native engine's "
                    "key-striped reducer plane (0 = engine default "
                    "min(4, cores); 1 = striping off, inline sums on the "
                    "serve threads) — the striped-vs-single A/B column of "
                    "SCALING_r06.json")
    ap.add_argument("--van", default="tcp", choices=["tcp", "uds", "shm"],
                    help="transport van for the PS data plane")
    ap.add_argument("--multiproc", action="store_true",
                    help="worker/server subprocesses instead of threads "
                    "(real parallelism; the recorded-artifact mode)")
    ap.add_argument("--worker-role", action="store_true",
                    help=argparse.SUPPRESS)  # internal: subprocess worker
    args = ap.parse_args()

    if args.worker_role:
        worker_main(args)
        return

    os.environ["BYTEPS_VAN"] = args.van
    if args.stripes > 0:
        # read by the C++ engine at server start (threads mode) and
        # inherited by server subprocesses (multiproc mode)
        os.environ["BYTEPS_SERVER_STRIPES"] = str(args.stripes)
    worker_counts = [int(w) for w in args.workers.split(",")]
    per_worker = int(args.mbytes * 1e6)
    results = {}
    for n in worker_counts:
        n_servers = args.servers if args.servers > 0 else n
        if args.multiproc:
            results[n] = measure_multiproc(n, n_servers, args)
        else:
            results[n] = measure(
                n, n_servers, args.keys, per_worker, args.rounds, args.native
            )

    base = worker_counts[0]
    # Aggregate-throughput retention: N workers push N× the total bytes,
    # so ideal pipelining keeps TOTAL bytes/s flat on a fixed CPU budget —
    # eff(N) = (N·payload/t_N) / (payload/t_1) · (1/N) · N = N·t_1/t_N / N
    # … i.e. throughput(N)/throughput(1) where throughput counts ALL
    # workers' bytes.  On real multi-host hardware (CPU scales with N)
    # this lower-bounds the reference's scaling-efficiency metric.
    thr = {n: n * args.mbytes / results[n] for n in worker_counts}
    retention = {n: thr[n] / thr[base] for n in worker_counts}
    top = worker_counts[-1]
    print(json.dumps({
        "metric": f"pushpull_throughput_retention_{top}w",
        "value": round(retention[top], 4),
        "unit": "ratio",
        "vs_baseline": round(retention[top] / 0.85, 4),  # >=85% north star
        "extra": {
            "van": args.van,
            "engine": "native" if args.native else "python",
            "stripes": args.stripes or "engine default",
            "multiproc": bool(args.multiproc),
            "round_time_s": {str(n): round(t, 4) for n, t in results.items()},
            "aggregate_mb_per_s": {str(n): round(t, 2) for n, t in thr.items()},
            "retention": {str(n): round(e, 4) for n, e in retention.items()},
            "servers": args.servers or "scaled with workers",
            "mbytes_per_worker": args.mbytes,
            "note": "loopback fake-cluster simulation on shared CPU (no "
                    "multi-host hardware in this environment): value is "
                    "aggregate PS-plane throughput at N workers vs "
                    f"{base} worker — flat (1.0) means the protocol adds "
                    "no superlinear overhead as the cluster grows; on real "
                    "hardware with per-node CPUs this lower-bounds the "
                    "reference's scaling-efficiency metric",
        },
    }))


if __name__ == "__main__":
    main()
