"""Server-side optimizer A/B: where does the optimizer state live?

The server-side optimizer plane (docs/architecture.md "Server-side
optimizer") moves the update rule to each key's owning server — workers
push gradients and pull updated parameters, so the per-worker Adam
moments (2x the model size, replicated on EVERY worker) become one
per-key copy on the PS fleet.  This bench measures exactly that trade
on a loopback fleet:

- **worker**: plain summation keys; the worker pulls averaged gradients
  and runs a local numpy Adam over its own slot arrays — the
  worker-resident optimizer-state bytes are the sum of those arrays.
- **server**: the same tensors declared ``byteps_server_opt="adam"``;
  the worker holds ZERO optimizer state and the pull returns the
  already-updated parameters.

Same tensor population, same step count, same wire; the phases differ
only in who runs the rule.  Output: ``SERVEROPT_BENCH_r01.json`` with
per-phase step times, worker optimizer-state bytes, and wire bytes —
the headline is worker state dropping to 0 with step time within noise
(the update itself is O(n) numpy either side of the wire).

    python tools/server_opt_bench.py --out SERVEROPT_BENCH_r01.json
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import subprocess
import sys
import threading

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: Adam hyperparameters — shared by both phases so the math is identical
HP = {"lr": 0.001}

_WORKER_BODY = r"""
import json, os, sys, time
import numpy as np
os.environ["JAX_PLATFORMS"] = "cpu"
import byteps_tpu as bps
from byteps_tpu.core.telemetry import counters

mode = os.environ["SOPT_BENCH_MODE"]          # "worker" | "server"
steps = int(os.environ["SOPT_BENCH_STEPS"])
dim = int(os.environ["SOPT_BENCH_DIM"])
nt = int(os.environ["SOPT_BENCH_TENSORS"])
hp = json.loads(os.environ["SOPT_BENCH_HP"])

bps.init()
rng = np.random.default_rng(7)
params = [rng.standard_normal(dim).astype(np.float32) for _ in range(nt)]
names = ["sopt.t%d" % i for i in range(nt)]

opt_bytes = 0
if mode == "server":
    for nm in names:
        bps.declare_tensor(nm, byteps_server_opt="adam",
                           byteps_server_opt_hp=hp)
    # seed round: every worker pushes its initial params, the servers
    # adopt them verbatim — also covers init barriers + allocation
    hs = [bps.push_pull_async(p, name=nm) for p, nm in zip(params, names)]
    params = [np.asarray(bps.synchronize(h)) for h in hs]
else:
    # worker-resident Adam: one m and one v slot per tensor — the bytes
    # this bench exists to count
    m = [np.zeros(dim, np.float32) for _ in range(nt)]
    v = [np.zeros(dim, np.float32) for _ in range(nt)]
    opt_bytes = sum(a.nbytes for a in m) + sum(a.nbytes for a in v)
    # warm-up round covers the same init barriers + allocation
    hs = [bps.push_pull_async(p, name=nm) for p, nm in zip(params, names)]
    for h in hs:
        bps.synchronize(h)

base = counters().snapshot()
one, b1, b2 = np.float32(1), np.float32(0.9), np.float32(0.999)
eps, lr = np.float32(1e-8), np.float32(hp["lr"])
times, t_step = [], 0
for s in range(steps):
    grads = [rng.standard_normal(dim).astype(np.float32) for _ in range(nt)]
    t0 = time.monotonic()
    hs = [bps.push_pull_async(g, name=nm) for g, nm in zip(grads, names)]
    outs = [np.asarray(bps.synchronize(h)) for h in hs]
    if mode == "worker":
        # outs are the averaged gradients: run Adam here, on local slots
        t_step += 1
        t = np.float32(t_step)
        for i, g in enumerate(outs):
            m[i] *= b1; m[i] += (one - b1) * g
            v[i] *= b2; v[i] += (one - b2) * (g * g)
            m_hat = m[i] / (one - b1 ** t)
            v_hat = v[i] / (one - b2 ** t)
            params[i] -= lr * (m_hat / (np.sqrt(v_hat) + eps))
    else:
        # outs ARE the updated parameters — nothing left to compute
        params = outs
    times.append(time.monotonic() - t0)
snap = counters().snapshot()
print("SOPT_RESULT " + json.dumps({
    "mode": mode, "times": times, "opt_state_bytes": opt_bytes,
    "push_bytes": snap.get("wire_tx_bytes", 0) - base.get("wire_tx_bytes", 0),
    "pull_bytes": snap.get("wire_rx_bytes", 0) - base.get("wire_rx_bytes", 0),
}))
sys.stdout.flush()
bps.shutdown()
"""


def _percentile(vals, q):
    vals = sorted(vals)
    if not vals:
        return 0.0
    i = min(len(vals) - 1, int(q * (len(vals) - 1)))
    return vals[i]


def run_phase(mode: str, steps: int, dim: int, tensors: int,
              servers: int = 2) -> dict:
    """One fresh fleet (scheduler + Python-engine servers) + one
    subprocess worker running the phase body; returns its stats."""
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "BYTEPS_VAN": "tcp",
        "BYTEPS_HEARTBEAT_INTERVAL": "1",
        "BYTEPS_FORCE_DISTRIBUTED": "1",
        "DMLC_NUM_WORKER": "1",
        "DMLC_NUM_SERVER": str(servers),
        "DMLC_PS_ROOT_URI": "127.0.0.1",
        "SOPT_BENCH_MODE": mode,
        "SOPT_BENCH_STEPS": str(steps),
        "SOPT_BENCH_DIM": str(dim),
        "SOPT_BENCH_TENSORS": str(tensors),
        "SOPT_BENCH_HP": json.dumps(HP),
    }
    env.pop("BYTEPS_SERVER_OPT", None)  # per-tensor kwargs only
    os.environ.update({k: env[k] for k in (
        "BYTEPS_VAN", "DMLC_NUM_WORKER", "DMLC_NUM_SERVER",
        "DMLC_PS_ROOT_URI",
    )})

    from byteps_tpu.common.config import Config
    from byteps_tpu.comm.rendezvous import Scheduler
    from byteps_tpu.server.server import PSServer

    sched = Scheduler(num_workers=1, num_servers=servers, host="127.0.0.1")
    sched.start()
    os.environ["DMLC_PS_ROOT_PORT"] = str(sched.port)
    env["DMLC_PS_ROOT_PORT"] = str(sched.port)
    fleet = [PSServer(Config.from_env()) for _ in range(servers)]
    for srv in fleet:
        threading.Thread(target=srv.start, daemon=True).start()

    try:
        proc = subprocess.run(
            [sys.executable, "-c", _WORKER_BODY], env=env,
            capture_output=True, text=True, timeout=600, cwd=REPO,
        )
        result = None
        for line in proc.stdout.splitlines():
            if line.startswith("SOPT_RESULT "):
                result = json.loads(line[len("SOPT_RESULT "):])
        if proc.returncode != 0 or result is None:
            raise RuntimeError(
                f"phase {mode} worker failed (rc={proc.returncode}):\n"
                f"{proc.stderr[-2000:]}"
            )
    finally:
        for srv in fleet:
            srv.stop()
        sched.stop()

    times = result["times"]
    stats = {
        "steps": len(times),
        "worker_opt_state_bytes": result["opt_state_bytes"],
        "p50_ms": round(_percentile(times, 0.50) * 1e3, 2),
        "p99_ms": round(_percentile(times, 0.99) * 1e3, 2),
        "mean_ms": round(statistics.fmean(times) * 1e3, 2),
        "push_bytes_per_step": result["push_bytes"] // max(1, len(times)),
        "pull_bytes_per_step": result["pull_bytes"] // max(1, len(times)),
    }
    print(f"  phase {mode:6s}: opt_state={stats['worker_opt_state_bytes']}B "
          f"mean={stats['mean_ms']}ms p99={stats['p99_ms']}ms "
          f"pull/step={stats['pull_bytes_per_step']}B")
    return stats


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--dim", type=int, default=1 << 16,
                    help="floats per tensor")
    ap.add_argument("--tensors", type=int, default=8)
    ap.add_argument("--servers", type=int, default=2)
    ap.add_argument("--out", default="SERVEROPT_BENCH_r01.json")
    args = ap.parse_args()

    model_bytes = args.dim * 4 * args.tensors
    print(f"server_opt_bench: {args.tensors} x {args.dim} f32 "
          f"({model_bytes // 1024} KiB model), {args.servers} servers, "
          f"adam {HP}")
    worker = run_phase("worker", args.steps, args.dim, args.tensors,
                       args.servers)
    server = run_phase("server", args.steps, args.dim, args.tensors,
                       args.servers)

    result = {
        "config": {
            "tensors": args.tensors, "dim": args.dim,
            "model_bytes": model_bytes, "servers": args.servers,
            "steps": args.steps, "rule": "adam", "hp": HP,
        },
        "phases": {"worker": worker, "server": server},
        "headline": {
            # the ZeRO-for-PS claim: per-worker optimizer state → 0
            "worker_opt_state_bytes": worker["worker_opt_state_bytes"],
            "server_opt_state_bytes": server["worker_opt_state_bytes"],
            "step_time_ratio_server_over_worker": round(
                server["mean_ms"] / max(0.01, worker["mean_ms"]), 3
            ),
            "pull_bytes_ratio_server_over_worker": round(
                server["pull_bytes_per_step"]
                / max(1, worker["pull_bytes_per_step"]), 3
            ),
        },
    }
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
        f.write("\n")
    print(json.dumps(result["headline"], indent=2))
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
