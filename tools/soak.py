"""Randomized composition soak for the PS plane (bug-finder, not a CI
test).

Nothing in tests/ composes ALL the moving parts at once: elastic
suspend/resume with changing server counts, compression (host AND
device-codec paths), link shaping, partitioning, row-sparse, async
handles, and priorities — under one engine across many generations.
This tool does, with a seedable RNG and correctness checks on every
round (1 worker ⇒ push_pull is identity; any mismatch or hang is a
found bug).

    python tools/soak.py --seconds 300 [--seed 7] [--shaped]

Exit 0 = survived with all invariants held; any exception/timeout is a
reproducible failure (seed printed).  The r4 torn-counter and r4
re-init-cycle bugs are exactly the class this harness hunts.
"""

from __future__ import annotations

import argparse
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=2")
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seconds", type=float, default=120.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--shaped", action="store_true",
                    help="run under BYTEPS_VAN_DELAY_MS/RATE shaping")
    ap.add_argument("--van", default="tcp", choices=["tcp", "uds", "shm"])
    args = ap.parse_args()

    rng = np.random.default_rng(args.seed)
    if args.shaped:
        os.environ["BYTEPS_VAN_DELAY_MS"] = "2"
        os.environ["BYTEPS_VAN_RATE_MBYTES_S"] = "200"
    os.environ["BYTEPS_VAN"] = args.van
    os.environ["BYTEPS_MIN_COMPRESS_BYTES"] = "0"
    os.environ["BYTEPS_PARTITION_BYTES"] = "4096"
    os.environ["BYTEPS_HEARTBEAT_INTERVAL"] = "0.2"

    from byteps_tpu.common.config import Config
    from byteps_tpu.comm.rendezvous import Scheduler
    from byteps_tpu.server.server import PSServer

    sched = Scheduler(num_workers=1, num_servers=1, host="127.0.0.1")
    sched.start()
    os.environ.update({
        "DMLC_PS_ROOT_URI": "127.0.0.1",
        "DMLC_PS_ROOT_PORT": str(sched.port),
        "DMLC_NUM_WORKER": "1",
        "DMLC_NUM_SERVER": "1",
        "BYTEPS_FORCE_DISTRIBUTED": "1",
    })
    servers = [PSServer(Config.from_env())]
    threading.Thread(target=servers[0].start, daemon=True).start()

    import byteps_tpu as bps

    bps.init()
    import jax.numpy as jnp

    stats = {"rounds": 0, "resizes": 0, "compressed": 0, "device": 0,
             "rowsparse": 0, "async": 0}
    declared: dict = {}
    t_end = time.monotonic() + args.seconds
    step = 0
    try:
        while time.monotonic() < t_end:
            step += 1
            roll = rng.random()
            if roll < 0.04 and stats["rounds"] > 3:
                # elastic resize: 1<->2 servers through suspend/resume —
                # the resuming worker's register carries the new count;
                # on scale-down the SCHEDULER shutdowns the dropped server
                want = 2 if len(servers) == 1 else 1
                bps.suspend()
                os.environ["DMLC_NUM_SERVER"] = str(want)
                if want == 2:
                    # the resuming worker's register announces the new
                    # topology (and PARKS until server 2 joins) — it must
                    # reach the scheduler BEFORE the new server dials in,
                    # or that server is refused as an over-capacity join
                    rt = threading.Thread(
                        target=lambda: bps.resume(num_servers=2), daemon=True
                    )
                    rt.start()
                    for _ in range(200):
                        if sched.num_servers == 2:
                            break
                        time.sleep(0.05)
                    srv = PSServer(Config.from_env())
                    servers.append(srv)
                    threading.Thread(target=srv.start, daemon=True).start()
                    rt.join(30)
                    if rt.is_alive():
                        raise RuntimeError("resume parked forever at scale-up")
                else:
                    bps.resume(num_servers=1)
                    dropped = servers.pop()
                    for _ in range(200):
                        if dropped._stop.is_set():
                            break
                        time.sleep(0.05)
                stats["resizes"] += 1
                continue
            name = f"soak.t{rng.integers(0, 12)}"
            n = int(rng.integers(64, 6000))
            if name in declared:
                n = declared[name]  # size is sticky per name
            x = rng.normal(size=n).astype(np.float32)
            kind = rng.random()
            if name not in declared:
                if kind < 0.25:
                    # lossless-at-full-k codec so identity still holds
                    bps.declare_tensor(
                        name, byteps_compressor_type="topk",
                        byteps_compressor_k=str(4096 // 4),
                    )
                declared[name] = n
            if kind < 0.25:
                stats["compressed"] += 1
                if rng.random() < 0.4:
                    stats["device"] += 1
                    out = bps.push_pull(jnp.asarray(x), name=name, average=False)
                else:
                    out = bps.push_pull(x, name=name, average=False)
                np.testing.assert_allclose(np.asarray(out), x, rtol=1e-5,
                                           atol=1e-6)
            elif kind < 0.35:
                stats["rowsparse"] += 1
                rows, dim = 40, 8
                rs_name = f"soak.rs{rng.integers(0, 3)}"
                idx = np.unique(
                    rng.integers(0, rows, size=int(rng.integers(1, 10)))
                ).astype(np.int64)
                vals = rng.normal(size=(idx.size, dim)).astype(np.float32)
                out = bps.push_pull_rowsparse(
                    idx, vals, rs_name, total_rows=rows, average=False
                )
                # result is already gathered at the pushed indices
                np.testing.assert_allclose(np.asarray(out), vals, rtol=1e-6)
            elif kind < 0.55:
                stats["async"] += 1
                hs = [
                    bps.push_pull_async(
                        x + i, name=name, average=False,
                        priority=int(rng.integers(-5, 5)),
                    )
                    for i in range(3)
                ]
                for i, h in enumerate(hs):
                    np.testing.assert_allclose(
                        np.asarray(bps.synchronize(h)), x + i, rtol=1e-6
                    )
            else:
                out = bps.push_pull(x, name=name, average=False)
                np.testing.assert_allclose(np.asarray(out), x, rtol=1e-6)
            stats["rounds"] += 1
        bps.shutdown()
    except BaseException:
        print(f"SOAK FAILED at step {step} seed={args.seed} stats={stats}",
              file=sys.stderr, flush=True)
        raise
    finally:
        for srv in servers:
            srv.stop()
        sched.stop()
    print(f"SOAK OK: {stats} (seed={args.seed}, {args.seconds:.0f}s)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
