"""Quantify the TF plugin's tf.py_function overhead (VERDICT r3 weak #4).

The TensorFlow plugin routes every reduce through a ``tf.py_function``
host callback (byteps_tpu/tensorflow/ops.py) — functionally correct, but
each call is a serialized TF-runtime→host hop.  This tool measures what
that hop costs against the same traffic through the core API directly,
and how much ``push_pull_group`` (one host hop for N tensors) claws back:

  core        — byteps_tpu.push_pull_async/synchronize straight from numpy
  tf-per-op   — byteps_tpu.tensorflow.push_pull once per tensor
  tf-grouped  — byteps_tpu.tensorflow.push_pull_group (one py_function)

Run on the CPU mesh (local mode: the reduce itself is an ICI psum
identity on 1 worker, so the measured delta IS the wrapping overhead):

    JAX_PLATFORMS=cpu python tools/tf_overhead_bench.py

Prints one JSON line (checked in as TF_OVERHEAD_r{N}.json).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # the axon site hook overrides the env var; the config update is the
    # only way to actually get the CPU backend (see .claude verify notes)
    import jax

    jax.config.update("jax_platforms", "cpu")


def main() -> None:
    import numpy as np

    import byteps_tpu as bps
    from byteps_tpu import tensorflow as bps_tf
    from byteps_tpu.tensorflow.ops import push_pull_group_fused

    bps.init()

    # a small model's gradient list: 30 tensors, mixed sizes
    rng = np.random.default_rng(0)
    shapes = [(256, 256)] * 10 + [(1024,)] * 10 + [(64, 64)] * 10
    grads = [rng.normal(size=s).astype(np.float32) for s in shapes]
    names = [f"tfo.g{i}" for i in range(len(grads))]
    rounds = 30

    def run_core() -> float:
        t0 = time.perf_counter()
        for _ in range(rounds):
            hs = [
                bps.push_pull_async(g, name=n, average=False, priority=-i)
                for i, (g, n) in enumerate(zip(grads, names))
            ]
            for h in hs:
                bps.synchronize(h)
        return (time.perf_counter() - t0) / rounds

    def run_tf_per_op() -> float:
        import tensorflow as tf

        ts = [tf.constant(g) for g in grads]
        t0 = time.perf_counter()
        for _ in range(rounds):
            outs = [
                bps_tf.push_pull(t, name=n, average=False)
                for t, n in zip(ts, names)
            ]
            _ = [np.asarray(o) for o in outs]
        return (time.perf_counter() - t0) / rounds

    def run_tf_grouped() -> float:
        import tensorflow as tf

        ts = [tf.constant(g) for g in grads]
        t0 = time.perf_counter()
        for _ in range(rounds):
            outs = bps_tf.push_pull_group(ts, names, average=False)
            _ = [np.asarray(o) for o in outs]
        return (time.perf_counter() - t0) / rounds

    def run_tf_fused() -> float:
        import tensorflow as tf

        ts = [tf.constant(g) for g in grads]
        t0 = time.perf_counter()
        for _ in range(rounds):
            outs = push_pull_group_fused(ts, names, average=False)
            _ = [np.asarray(o) for o in outs]
        return (time.perf_counter() - t0) / rounds

    def run_in_function(fn) -> float:
        """Keras-real mode: the sync inside ONE tf.function — in-graph
        ops compile away, py_function host hops remain per call."""
        import tensorflow as tf

        ts = [tf.constant(g) for g in grads]

        @tf.function
        def step():
            return fn(ts, names, average=False)

        _ = [np.asarray(o) for o in step()]  # trace once
        t0 = time.perf_counter()
        for _ in range(rounds):
            _ = [np.asarray(o) for o in step()]
        return (time.perf_counter() - t0) / rounds

    # short warmups (tensor declaration, trace caches) — the measured
    # loops amortize any residual cold cost over 30 rounds
    for _ in range(3):
        hs = [bps.push_pull_async(g, name=n, average=False)
              for g, n in zip(grads, names)]
        for h in hs:
            bps.synchronize(h)
    import tensorflow as tf
    warm = [tf.constant(g) for g in grads[:2]]
    for _ in range(3):
        [np.asarray(o) for o in (
            bps_tf.push_pull(warm[0], name=names[0], average=False),
            bps_tf.push_pull(warm[1], name=names[1], average=False),
        )]
        [np.asarray(o) for o in bps_tf.push_pull_group(
            warm, names[:2], average=False)]
        [np.asarray(o) for o in push_pull_group_fused(
            warm, names[:2], average=False)]
    core_s = run_core()
    per_op_s = run_tf_per_op()
    grouped_s = run_tf_grouped()
    fused_s = run_tf_fused()
    grouped_fn_s = run_in_function(bps_tf.push_pull_group)
    fused_fn_s = run_in_function(push_pull_group_fused)
    bps.shutdown()

    print(json.dumps({
        "metric": "tf_plugin_overhead_per_step_ms",
        "tensors_per_step": len(grads),
        "payload_mbytes": round(sum(g.nbytes for g in grads) / 1e6, 2),
        "rounds": rounds,
        "core_ms": round(core_s * 1e3, 2),
        "tf_per_op_ms": round(per_op_s * 1e3, 2),
        "tf_grouped_ms": round(grouped_s * 1e3, 2),
        "tf_fused_ms": round(fused_s * 1e3, 2),
        "tf_grouped_in_function_ms": round(grouped_fn_s * 1e3, 2),
        "tf_fused_in_function_ms": round(fused_fn_s * 1e3, 2),
        "per_op_overhead_x": round(per_op_s / core_s, 2),
        "grouped_overhead_x": round(grouped_s / core_s, 2),
        "fused_overhead_x": round(fused_s / core_s, 2),
        "notes": (
            "local mode on the CPU mesh: the reduce is an identity psum, so "
            "deltas are pure wrapping cost; tf-per-op pays one py_function "
            "host hop per tensor, push_pull_group batches all tensors into "
            "one hop; push_pull_group_fused additionally concats per dtype "
            "IN-GRAPH so the hop marshals/submits one tensor per dtype — "
            "the shipped default for the gradient-sync path "
            "(BYTEPS_TF_FUSION=0 restores per-tensor keys)"
        ),
    }))


if __name__ == "__main__":
    main()
