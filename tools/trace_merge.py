#!/usr/bin/env python
"""Stitch per-process byteps trace files into ONE cross-process timeline.

Each worker writes ``<trace_dir>/<local_rank>/comm.json`` and each Python
server ``<trace_dir>/server<rank>/comm.json`` (core/tracing.py).  Span
events carry wire-propagated trace/span ids (docs/observability.md), so a
worker's PUSH span and the server's recv→sum→publish→reply children share
a trace id — but they live in separate files.  This tool:

1. collects every ``comm.json`` under the given directories (or explicit
   file paths),
2. keeps per-process identity: span events already carry a ``pid`` like
   ``worker0`` / ``server1``; per-tensor stage envelopes (whose pid is
   the tensor name) are namespaced per source file so two workers' rows
   don't collide,
3. emits Chrome trace FLOW events (``ph: s/f``) linking every
   parent→child span pair found across processes, so Perfetto draws
   arrows from the worker RPC span into the server's child spans,
4. writes one merged Perfetto-loadable JSON.

Usage:

    python tools/trace_merge.py -o merged.json TRACE_DIR [TRACE_DIR ...]

Demo recipe (2 workers / 1 server, fused + chaos): docs/observability.md.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Tuple


def find_trace_files(paths: List[str]) -> List[str]:
    out = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for root, _dirs, files in os.walk(p):
            for f in files:
                if f.endswith(".json") and f.startswith("comm"):
                    out.append(os.path.join(root, f))
    return sorted(set(out))


def _source_tag(path: str) -> str:
    """A short per-file namespace: the containing directory name
    (``0``, ``1``, ``server0``, …)."""
    return os.path.basename(os.path.dirname(os.path.abspath(path))) or "trace"


def merge(files: List[str]) -> dict:
    events: List[dict] = []
    #: span id (hex) → (pid, tid, ts_us, dur_us) of the span that OWNS it
    by_span: Dict[str, Tuple[str, str, float, float]] = {}
    #: (child span ref) parent id (hex) → list of child event tuples
    child_refs: List[Tuple[str, str, str, float]] = []

    for path in files:
        tag = _source_tag(path)
        try:
            with open(path) as f:
                payload = json.load(f)
        except (OSError, ValueError) as e:
            print(f"warning: skipping {path}: {e}", file=sys.stderr)
            continue
        for ev in payload.get("traceEvents", []):
            ev = dict(ev)
            args = ev.get("args") or {}
            if ev.get("cat") == "span":
                # cross-process identity is already in pid (worker0 …)
                span = args.get("span")
                if span and ev.get("ph") == "X":
                    prev = by_span.get(span)
                    # keep the EARLIEST event as the span's anchor (a
                    # task's first stage), so flow arrows start where
                    # the work did
                    if prev is None or ev["ts"] < prev[2]:
                        by_span[span] = (
                            ev["pid"], ev["tid"], ev["ts"], ev.get("dur", 0)
                        )
                parent = args.get("parent")
                if parent:
                    child_refs.append(
                        (parent, ev["pid"], ev["tid"], ev["ts"])
                    )
            else:
                # per-tensor stage envelope: namespace the tensor-name pid
                # per source process so two ranks' rows stay separate
                ev["pid"] = f"{tag}:{ev.get('pid', '')}"
            events.append(ev)

    # flow events: arrow from the parent span (worker RPC) to each child
    # (server-side stage).  One flow id per parent span.
    flow_id = 0
    seen_parent_flow: Dict[str, int] = {}
    flows: List[dict] = []
    for parent, cpid, ctid, cts in child_refs:
        anchor = by_span.get(parent)
        if anchor is None:
            continue  # parent span's process wasn't merged in
        ppid, ptid, pts, pdur = anchor
        fid = seen_parent_flow.get(parent)
        if fid is None:
            flow_id += 1
            fid = seen_parent_flow[parent] = flow_id
            flows.append({
                "name": "rpc", "cat": "flow", "ph": "s", "id": fid,
                "ts": pts + max(0.0, pdur) / 2, "pid": ppid, "tid": ptid,
            })
        flows.append({
            "name": "rpc", "cat": "flow", "ph": "f", "bp": "e", "id": fid,
            "ts": cts, "pid": cpid, "tid": ctid,
        })
    events.extend(flows)
    events.sort(key=lambda e: e.get("ts", 0))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "merged_from": files,
            "linked_spans": len(seen_parent_flow),
            "cross_process_children": len(child_refs),
        },
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="+",
                    help="trace dirs (searched recursively) or comm.json files")
    ap.add_argument("-o", "--output", default="merged_trace.json")
    args = ap.parse_args(argv)
    files = find_trace_files(args.paths)
    if not files:
        print("no comm*.json trace files found", file=sys.stderr)
        return 1
    merged = merge(files)
    with open(args.output, "w") as f:
        json.dump(merged, f)
    meta = merged["otherData"]
    print(
        f"merged {len(files)} file(s) → {args.output}: "
        f"{len(merged['traceEvents'])} events, "
        f"{meta['linked_spans']} linked spans, "
        f"{meta['cross_process_children']} cross-process children"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
