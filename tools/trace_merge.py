#!/usr/bin/env python
"""Stitch per-process byteps trace files into ONE cross-process timeline.

Each worker writes ``<trace_dir>/<local_rank>/comm.json`` and each server
(Python engine directly, native C++ engine via the span-ring drain in
NativePSServer) ``<trace_dir>/server<rank>/comm.json`` (core/tracing.py).
Span events carry wire-propagated trace/span ids (docs/observability.md),
so a worker's PUSH span and the server's recv→sum→publish→reply children
share a trace id — but they live in separate files.  This tool:

1. collects every ``comm.json`` under the given directories (or explicit
   file paths),
2. keeps per-process identity: span events already carry a ``pid`` like
   ``worker0`` / ``server1``; per-tensor stage envelopes (whose pid is
   the tensor name) are namespaced per source file so two workers' rows
   don't collide,
3. emits Chrome trace FLOW events (``ph: s/f``) linking every
   parent→child span pair found across processes, so Perfetto draws
   arrows from the worker RPC span into the server's child spans,
4. counts ORPHANED children (parent id never seen — a missing server or
   worker file) instead of silently dropping the arrow: a clean-looking
   merge that actually lost a process now says so,
5. writes one merged Perfetto-loadable JSON.

Usage:

    python tools/trace_merge.py -o merged.json TRACE_DIR [TRACE_DIR ...]

``--critical-path ATTRIB.json`` additionally walks the merged flow graph
and attributes where the time of one training step went — engine-queue
wait vs wire vs sum vs publish vs reply, split per engine (``python`` /
``native``; native server children are tagged ``engine: "native"`` by
the drain) — the baseline artifact the multi-core key-striping work is
judged against (TRACE_ATTRIB_r06.json).  Reducer-lane spans (the drain
puts each stripe on its own ``stripe<N>`` Perfetto track) additionally
get a per-stripe **occupancy** split — stripe identity comes from the
span's ``stripe`` arg or, failing that, its ``stripe<N>`` tid — and the
occupancy is fed straight into the SAME ``hot_stripe`` trigger rule the
on-node flight recorder runs (core/flightrec.py), so a skewed key hash
found in an offline trace and one caught live by the flight recorder
are judged by one rule, not two drifting reimplementations.

Demo recipe (2 workers / 1 server, fused + chaos): docs/observability.md.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple


def find_trace_files(paths: List[str]) -> List[str]:
    out = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for root, _dirs, files in os.walk(p):
            for f in files:
                if f.endswith(".json") and f.startswith("comm"):
                    out.append(os.path.join(root, f))
    return sorted(set(out))


def _source_tag(path: str) -> str:
    """A short per-file namespace: the containing directory name
    (``0``, ``1``, ``server0``, …)."""
    return os.path.basename(os.path.dirname(os.path.abspath(path))) or "trace"


def merge(files: List[str]) -> dict:
    events: List[dict] = []
    #: span id (hex) → (pid, tid, ts_us, dur_us) of the span that OWNS it
    by_span: Dict[str, Tuple[str, str, float, float]] = {}
    #: (child span ref) parent id (hex) → list of child event tuples
    child_refs: List[Tuple[str, str, str, float]] = []

    for path in files:
        tag = _source_tag(path)
        try:
            with open(path) as f:
                payload = json.load(f)
        except (OSError, ValueError) as e:
            print(f"warning: skipping {path}: {e}", file=sys.stderr)
            continue
        for ev in payload.get("traceEvents", []):
            ev = dict(ev)
            args = ev.get("args") or {}
            if ev.get("cat") == "span":
                # cross-process identity is already in pid (worker0 …)
                span = args.get("span")
                if span and ev.get("ph") == "X":
                    prev = by_span.get(span)
                    # keep the EARLIEST event as the span's anchor (a
                    # task's first stage), so flow arrows start where
                    # the work did
                    if prev is None or ev["ts"] < prev[2]:
                        by_span[span] = (
                            ev["pid"], ev["tid"], ev["ts"], ev.get("dur", 0)
                        )
                parent = args.get("parent")
                if parent:
                    child_refs.append(
                        (parent, ev["pid"], ev["tid"], ev["ts"])
                    )
            else:
                # per-tensor stage envelope: namespace the tensor-name pid
                # per source process so two ranks' rows stay separate
                ev["pid"] = f"{tag}:{ev.get('pid', '')}"
            events.append(ev)

    # flow events: arrow from the parent span (worker RPC) to each child
    # (server-side stage).  One flow id per parent span.  A child whose
    # parent was never merged in (missing worker/server file, dropped
    # window) is an ORPHAN — counted, not silently armless.
    flow_id = 0
    seen_parent_flow: Dict[str, int] = {}
    orphan_parents: Dict[str, int] = {}
    flows: List[dict] = []
    for parent, cpid, ctid, cts in child_refs:
        anchor = by_span.get(parent)
        if anchor is None:
            orphan_parents[parent] = orphan_parents.get(parent, 0) + 1
            continue  # parent span's process wasn't merged in
        ppid, ptid, pts, pdur = anchor
        fid = seen_parent_flow.get(parent)
        if fid is None:
            flow_id += 1
            fid = seen_parent_flow[parent] = flow_id
            flows.append({
                "name": "rpc", "cat": "flow", "ph": "s", "id": fid,
                "ts": pts + max(0.0, pdur) / 2, "pid": ppid, "tid": ptid,
            })
        flows.append({
            "name": "rpc", "cat": "flow", "ph": "f", "bp": "e", "id": fid,
            "ts": cts, "pid": cpid, "tid": ctid,
        })
    events.extend(flows)
    events.sort(key=lambda e: e.get("ts", 0))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "merged_from": files,
            "linked_spans": len(seen_parent_flow),
            "cross_process_children": len(child_refs),
            # children whose parent id never appeared in any merged file
            # — usually a process whose trace file is missing entirely
            "orphaned_spans": sum(orphan_parents.values()),
            "orphaned_parent_ids": len(orphan_parents),
        },
    }


# --- critical-path attribution (docs/observability.md) ---------------------
#
# Walk the merged flow graph: every server child span names its stage
# (recv = engine-queue wait, sum, publish, reply, resync) and parents
# onto the worker span that caused it.  The worker side of the same RPC
# is the PUSH / PULL / FUSE stage event carrying that span id.  Whatever
# part of the worker-observed RPC the server stages don't cover is wire
# + client overhead.  Aggregated per engine (the native server's drained
# children carry ``engine: "native"``), per stage, and per trace (one
# push_pull invocation = one trace = one step's worth of one tensor).

#: worker pipeline stages that bound one wire RPC (engine.py stage names)
_RPC_STAGES = {"PUSH", "PULL", "FUSE", "RESYNC", "INIT"}
_SERVER_STAGES = ("recv", "sum", "publish", "reply", "resync")

#: reducer-lane track names the span drain emits (server.py
#: ``_drain_spans_once``): one Perfetto thread per stripe
_STRIPE_TID = re.compile(r"^stripe(\d+)$")


def _span_stripe(args: dict, tid) -> Optional[int]:
    """Which reducer stripe executed a server child span: the explicit
    ``stripe`` arg when the drain stamped one, else derived from the
    ``stripe<N>`` track (tid) the drain files every reducer-lane span
    under.  None = a serve/control-thread span (``key<K>`` tracks)."""
    s = (args or {}).get("stripe")
    if s is not None:
        try:
            return int(s)
        except (TypeError, ValueError):
            return None
    m = _STRIPE_TID.match(str(tid or ""))
    return int(m.group(1)) if m else None


def _eval_hot_stripe(busy_us: Dict[str, float],
                     busy_n: Dict[str, int]) -> Optional[dict]:
    """Feed the per-stripe occupancy into the hot-stripe trigger rule
    the on-node flight recorder runs, verbatim: build the same record
    shape (``{"stripes": {stripe: {"n", "s"}}}``) and call
    ``flightrec._rule_hot_stripe`` with the same
    ``BYTEPS_FLIGHT_SLOW_FACTOR`` threshold.  Returns the rule's
    evidence dict (a confirmed hot stripe) or None — also None when the
    byteps package isn't importable (this tool stays runnable on a box
    that only has the trace files)."""
    try:
        from byteps_tpu.core.flightrec import _rule_hot_stripe
    except ImportError:
        sys.path.insert(
            0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        try:
            from byteps_tpu.core.flightrec import _rule_hot_stripe
        except ImportError:
            return None
    try:
        factor = float(os.environ.get("BYTEPS_FLIGHT_SLOW_FACTOR") or 3.0)
    except ValueError:
        factor = 3.0
    shim = type("_Rec", (), {"slow_factor": factor})()
    record = {
        "stripes": {
            s: {"n": busy_n.get(s, 0), "s": us / 1e6}
            for s, us in busy_us.items()
        }
    }
    return _rule_hot_stripe(shim, record)


def critical_path(merged: dict) -> dict:
    #: parent span id → {"extent": [min_ts, max_end] of worker RPC-stage
    #: events, "any": [min_ts, max_end] of ANY owning event}
    parents: Dict[str, dict] = {}
    #: parent span id → list of child dicts
    children: Dict[str, List[dict]] = {}
    traces = set()
    for ev in merged.get("traceEvents", []):
        if ev.get("cat") != "span" or ev.get("ph") != "X":
            continue
        args = ev.get("args") or {}
        span, parent = args.get("span"), args.get("parent")
        if args.get("trace"):
            traces.add(args["trace"])
        if parent:
            children.setdefault(parent, []).append({
                "name": ev.get("name", ""),
                "ts": float(ev.get("ts", 0.0)),
                "dur": float(ev.get("dur", 0.0)),
                "engine": args.get("engine", "python"),
                # reducer lane (native key-striped engine): which stripe
                # thread executed this stage — explicit arg or the
                # stripe<N> track the drain filed it under; None = a
                # serve/control thread
                "stripe": _span_stripe(args, ev.get("tid")),
            })
            continue
        if span:
            p = parents.setdefault(span, {"extent": None, "any": None})
            t0 = float(ev.get("ts", 0.0))
            t1 = t0 + float(ev.get("dur", 0.0))
            which = "extent" if ev.get("name") in _RPC_STAGES else "any"
            cur = p[which]
            if cur is None:
                p[which] = [t0, t1]
            else:
                cur[0] = min(cur[0], t0)
                cur[1] = max(cur[1], t1)

    engines: Dict[str, dict] = {}
    for parent, kids in children.items():
        engine = kids[0]["engine"]
        agg = engines.setdefault(engine, {
            "rpcs": 0,
            "stages_us": {s: 0.0 for s in _SERVER_STAGES},
            "wire_us": 0.0,
            "wire_rpcs": 0,
            "stripe_sum_us": {},
            "stripe_busy_us": {},
            "stripe_busy_n": {},
        })
        agg["rpcs"] += 1
        srv0, srv1 = None, None
        for k in kids:
            if k["name"] in agg["stages_us"]:
                agg["stages_us"][k["name"]] += k["dur"]
                # per-reducer sum time (native striped engine): split by
                # the stripe lane that executed it, so a bad key hash
                # shows up as one runaway reducer in the attribution
                if k["name"] == "sum" and k.get("stripe") is not None:
                    per = agg["stripe_sum_us"]
                    per[str(k["stripe"])] = (
                        per.get(str(k["stripe"]), 0.0) + k["dur"]
                    )
            # lane OCCUPANCY: every stage a stripe thread executed, not
            # just sum — a reducer drowning in publish fan-out is just as
            # hot as one drowning in summation, and this is the feed the
            # hot-stripe trigger rule judges
            if k.get("stripe") is not None:
                lane = str(k["stripe"])
                agg["stripe_busy_us"][lane] = (
                    agg["stripe_busy_us"].get(lane, 0.0) + k["dur"]
                )
                agg["stripe_busy_n"][lane] = (
                    agg["stripe_busy_n"].get(lane, 0) + 1
                )
            t0, t1 = k["ts"], k["ts"] + k["dur"]
            srv0 = t0 if srv0 is None else min(srv0, t0)
            srv1 = t1 if srv1 is None else max(srv1, t1)
        # wire + client overhead: the worker-observed RPC extent minus
        # the server-side extent.  Same-host clocks (the demo recipe)
        # make this meaningful; cross-host skew shows up as negative
        # and is floored.
        anchor = parents.get(parent)
        extent = anchor and (anchor["extent"] or anchor["any"])
        if extent is not None and srv0 is not None:
            wire = max(0.0, (extent[1] - extent[0]) - (srv1 - srv0))
            agg["wire_us"] += wire
            agg["wire_rpcs"] += 1

    out: Dict[str, dict] = {}
    for engine, agg in engines.items():
        total = sum(agg["stages_us"].values()) + agg["wire_us"]
        stages = {}
        for s in _SERVER_STAGES:
            us = agg["stages_us"][s]
            stages["queue_wait" if s == "recv" else s] = {
                "total_s": us / 1e6,
                "mean_s": us / 1e6 / agg["rpcs"] if agg["rpcs"] else 0.0,
                "share": us / total if total else 0.0,
            }
        stages["wire"] = {
            "total_s": agg["wire_us"] / 1e6,
            "mean_s": (agg["wire_us"] / 1e6 / agg["wire_rpcs"]
                       if agg["wire_rpcs"] else 0.0),
            "share": agg["wire_us"] / total if total else 0.0,
        }
        out[engine] = {"rpcs": agg["rpcs"], "stages": stages}
        lanes = sorted(
            set(agg["stripe_sum_us"]) | set(agg["stripe_busy_us"]),
            key=int,
        )
        if lanes:
            sum_total = sum(agg["stripe_sum_us"].values())
            busy_total = sum(agg["stripe_busy_us"].values())
            out[engine]["reducers"] = {}
            for stripe in lanes:
                sum_us = agg["stripe_sum_us"].get(stripe, 0.0)
                busy = agg["stripe_busy_us"].get(stripe, 0.0)
                out[engine]["reducers"][stripe] = {
                    "sum_total_s": sum_us / 1e6,
                    "share_of_sum": sum_us / sum_total if sum_total else 0.0,
                    "busy_total_s": busy / 1e6,
                    # this lane's share of all reducer busy time — the
                    # tid-occupancy view a hot stripe dominates
                    "occupancy": busy / busy_total if busy_total else 0.0,
                }
            hot = _eval_hot_stripe(agg["stripe_busy_us"],
                                   agg["stripe_busy_n"])
            if hot is not None:
                out[engine]["hot_stripe"] = hot
    return {
        "traces": len(traces),
        "linked_rpcs": sum(e["rpcs"] for e in out.values()),
        "orphaned_spans": merged.get("otherData", {}).get("orphaned_spans", 0),
        "engines": out,
    }


def _print_attribution(attrib: dict) -> None:
    print(
        f"critical path: {attrib['linked_rpcs']} linked RPC(s) across "
        f"{attrib['traces']} trace(s)"
    )
    for engine, agg in sorted(attrib["engines"].items()):
        print(f"  [{engine}] {agg['rpcs']} rpcs")
        for stage, d in agg["stages"].items():
            if d["total_s"] == 0.0:
                continue
            print(
                f"    {stage:<11s} {d['total_s'] * 1e3:9.3f} ms total  "
                f"{d['mean_s'] * 1e6:9.1f} µs/rpc  {d['share'] * 100:5.1f}%"
            )
        for stripe, d in agg.get("reducers", {}).items():
            print(
                f"    reducer {stripe:<3s} {d['sum_total_s'] * 1e3:9.3f} ms "
                f"sum   {d['share_of_sum'] * 100:5.1f}% of sum  "
                f"{d['occupancy'] * 100:5.1f}% occupancy"
            )
        hot = agg.get("hot_stripe")
        if hot:
            print(
                f"    HOT STRIPE: reducer {hot['stripe']} holds "
                f"{hot['share'] * 100:.0f}% of lane time "
                f"({hot['sum_seconds'] * 1e3:.3f} ms vs sibling median "
                f"{hot['sibling_median'] * 1e3:.3f} ms) — the flight "
                "recorder's hot_stripe rule fires on this trace; see "
                "docs/perf.md (BYTEPS_SERVER_STRIPES / key hash)"
            )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="+",
                    help="trace dirs (searched recursively) or comm.json files")
    ap.add_argument("-o", "--output", default="merged_trace.json")
    ap.add_argument(
        "--critical-path", metavar="ATTRIB_JSON", default=None,
        help="also walk the merged flow graph and write a per-engine, "
        "per-stage step-time attribution (queue wait / sum / publish / "
        "reply / wire) to this path",
    )
    args = ap.parse_args(argv)
    files = find_trace_files(args.paths)
    if not files:
        print("no comm*.json trace files found", file=sys.stderr)
        return 1
    merged = merge(files)
    with open(args.output, "w") as f:
        json.dump(merged, f)
    meta = merged["otherData"]
    orphan_note = ""
    if meta["orphaned_spans"]:
        orphan_note = (
            f", {meta['orphaned_spans']} ORPHANED span(s) across "
            f"{meta['orphaned_parent_ids']} missing parent id(s) — a "
            "process's trace file is probably missing"
        )
    print(
        f"merged {len(files)} file(s) → {args.output}: "
        f"{len(merged['traceEvents'])} events, "
        f"{meta['linked_spans']} linked spans, "
        f"{meta['cross_process_children']} cross-process children"
        f"{orphan_note}"
    )
    if args.critical_path:
        attrib = critical_path(merged)
        with open(args.critical_path, "w") as f:
            json.dump(attrib, f, indent=2, sort_keys=True)
        _print_attribution(attrib)
        print(f"attribution → {args.critical_path}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
