"""Van throughput microbenchmark — MB/s per transport, with copy audit.

Measures the PS data plane's hot path per van (tcp / uds / shm): one
worker drives push+pull rounds of a fixed payload against a live
in-process server, and reports payload MB/s plus how many pulls landed
zero-copy (received directly into the caller's result buffer — the
ps-lite ZPull-into-SArray property, core_loops.cc:571,609).

    python tools/van_bench.py [--mbytes 8] [--rounds 20] [--vans tcp,uds,shm]

Prints one JSON line per van.
"""

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np


def bench_van(van: str, mbytes: float, rounds: int) -> dict:
    from byteps_tpu.common.config import Config
    from byteps_tpu.comm.ps_client import PSClient
    from byteps_tpu.comm.rendezvous import Scheduler
    from byteps_tpu.server.server import PSServer

    os.environ["BYTEPS_VAN"] = van
    sched = Scheduler(num_workers=1, num_servers=1, host="127.0.0.1")
    sched.start()
    os.environ.update({
        "DMLC_PS_ROOT_URI": "127.0.0.1",
        "DMLC_PS_ROOT_PORT": str(sched.port),
        "DMLC_NUM_WORKER": "1",
        "DMLC_NUM_SERVER": "1",
        "BYTEPS_FORCE_DISTRIBUTED": "1",
    })
    cfg = Config.from_env()
    srv = PSServer(cfg)
    threading.Thread(target=srv.start, daemon=True).start()
    client = PSClient(cfg, node_uid="vb")
    client.connect()

    n = int(mbytes * 1e6) // 4
    payload = np.random.default_rng(0).normal(size=n).astype(np.float32)
    result = np.empty(n, dtype=np.float32)
    sink = memoryview(result).cast("B")
    client.init_tensor(1, n, 0)

    def round_once(version: int) -> None:
        done = threading.Event()
        state = [2]
        lock = threading.Lock()

        def dec(*_a):
            with lock:
                state[0] -= 1
                if state[0] == 0:
                    done.set()

        client.push(1, payload.data.cast("B"), 0, version, cb=dec)
        client.pull(1, version, dec, sink=sink)
        if not done.wait(60):
            raise RuntimeError(f"van {van} round timed out")

    for w in range(2):  # warmup
        round_once(w + 1)
    t0 = time.perf_counter()
    for r in range(rounds):
        round_once(r + 3)
    dt = time.perf_counter() - t0

    zero_copy = client.zero_copy_pulls
    client.close()
    srv.stop()
    sched.stop()
    # bytes moved per round: payload pushed + payload pulled
    mb = 2 * mbytes * rounds
    return {
        "van": van,
        "mb_per_s": round(mb / dt, 1),
        "round_ms": round(dt / rounds * 1e3, 2),
        "zero_copy_pulls": zero_copy,
        "total_pulls": rounds + 2,
        "mbytes_payload": mbytes,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mbytes", type=float, default=8.0)
    ap.add_argument("--rounds", type=int, default=20)
    ap.add_argument("--vans", default="tcp,uds,shm")
    args = ap.parse_args()
    for van in args.vans.split(","):
        van = van.strip()
        if van == "shm":
            import platform

            if platform.machine() not in ("x86_64", "AMD64", "i686"):
                print(json.dumps({"van": van, "skipped": "needs x86-64 TSO"}))
                continue
        print(json.dumps(bench_van(van, args.mbytes, args.rounds)))


if __name__ == "__main__":
    main()
