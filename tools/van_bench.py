"""Van throughput microbenchmark — MB/s per transport, with copy audit.

Measures the PS data plane's hot path per van (tcp / uds / shm): one
worker drives push+pull rounds of a fixed payload against a live
in-process server, and reports payload MB/s plus how many pulls landed
zero-copy (received directly into the caller's result buffer — the
ps-lite ZPull-into-SArray property, core_loops.cc:571,609).

    python tools/van_bench.py [--mbytes 8] [--rounds 20] [--vans tcp,uds,shm]

Prints one JSON line per van.
"""

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np


def _gil_contender(stop: "threading.Event") -> None:
    """Pure-Python busy loop: monopolizes the GIL the way engine-side
    Python work (compression staging, callback bookkeeping, framework
    glue) does in a real job.  Under this load the Python client's recv
    threads must win GIL slices to move bytes, while the native client's
    lanes only touch the GIL for the per-message completion callback."""
    x = 0
    while not stop.is_set():
        for _ in range(50000):
            x += 1


def bench_van(van: str, mbytes: float, rounds: int, engine: str = "python",
              streams: int = 1, n_keys: int = 1,
              client_kind: str = "python", contend: bool = False) -> dict:
    from byteps_tpu.common.config import Config
    from byteps_tpu.comm.ps_client import PSClient
    from byteps_tpu.comm.rendezvous import Scheduler
    from byteps_tpu.server.server import NativePSServer, PSServer

    os.environ["BYTEPS_VAN"] = van
    os.environ["BYTEPS_TCP_STREAMS"] = str(streams)
    # worker-side data plane: the C++ client (native/ps_client.cc) vs the
    # Python lanes — the VERDICT r3 #4 comparison axis
    os.environ["BYTEPS_NATIVE_CLIENT"] = "1" if client_kind == "native" else "0"
    sched = Scheduler(num_workers=1, num_servers=1, host="127.0.0.1")
    sched.start()
    os.environ.update({
        "DMLC_PS_ROOT_URI": "127.0.0.1",
        "DMLC_PS_ROOT_PORT": str(sched.port),
        "DMLC_NUM_WORKER": "1",
        "DMLC_NUM_SERVER": "1",
        "BYTEPS_FORCE_DISTRIBUTED": "1",
    })
    cfg = Config.from_env()
    srv = NativePSServer(cfg) if engine == "native" else PSServer(cfg)
    threading.Thread(target=srv.start, daemon=True).start()
    client = PSClient(cfg, node_uid="vb")
    client.connect()

    n = int(mbytes * 1e6) // 4 // n_keys
    keys = list(range(1, n_keys + 1))
    payloads = {
        k: np.random.default_rng(k).normal(size=n).astype(np.float32)
        for k in keys
    }
    results = {k: np.empty(n, dtype=np.float32) for k in keys}
    sinks = {k: memoryview(results[k]).cast("B") for k in keys}
    for k in keys:
        client.init_tensor(k, n, 0)

    def round_once(version: int) -> None:
        done = threading.Event()
        state = [2 * len(keys)]
        lock = threading.Lock()

        def dec(*_a):
            with lock:
                state[0] -= 1
                if state[0] == 0:
                    done.set()

        for k in keys:
            client.push(k, payloads[k].data.cast("B"), 0, version, cb=dec)
        for k in keys:
            client.pull(k, version, dec, sink=sinks[k])
        if not done.wait(60):
            raise RuntimeError(f"van {van} round timed out")

    for w in range(2):  # warmup
        round_once(w + 1)
    stop_contender = threading.Event()
    if contend:
        threading.Thread(
            target=_gil_contender, args=(stop_contender,), daemon=True
        ).start()
    try:
        t0 = time.perf_counter()
        for r in range(rounds):
            round_once(r + 3)
        dt = time.perf_counter() - t0
    finally:
        # a leaked contender would depress every later measurement
        stop_contender.set()

    zero_copy = client.zero_copy_pulls
    client.close()
    srv.stop()
    sched.stop()
    # bytes moved per round: payload pushed + payload pulled
    mb = 2 * mbytes * rounds
    return {
        "van": van,
        "engine": engine,
        "client": client_kind,
        "streams": streams,
        "contended": contend,
        "keys": n_keys,
        "mb_per_s": round(mb / dt, 1),
        "round_ms": round(dt / rounds * 1e3, 2),
        "zero_copy_pulls": zero_copy,
        "total_pulls": (rounds + 2) * n_keys,
        "mbytes_payload": mbytes,
    }


def bench_multistream(van: str, mbytes: float, rounds: int, n_clients: int,
                      stripes: int, n_keys: int = 8) -> dict:
    """The contended row (SCALING_r06 companion): N concurrent client
    connections drive same-key sum rounds against ONE native server, so
    every frame lands in the striped reducer plane under contention —
    the shape where `BYTEPS_SERVER_STRIPES` is supposed to pay.  Run at
    stripes=1 (single reducer) and stripes>=2 for the A/B."""
    from byteps_tpu.common.config import Config
    from byteps_tpu.comm.ps_client import PSClient
    from byteps_tpu.comm.rendezvous import Scheduler
    from byteps_tpu.server.server import NativePSServer

    os.environ["BYTEPS_VAN"] = van
    os.environ["BYTEPS_SERVER_STRIPES"] = str(stripes)
    os.environ["BYTEPS_NATIVE_CLIENT"] = "0"
    sched = Scheduler(num_workers=n_clients, num_servers=1, host="127.0.0.1")
    sched.start()
    os.environ.update({
        "DMLC_PS_ROOT_URI": "127.0.0.1",
        "DMLC_PS_ROOT_PORT": str(sched.port),
        "DMLC_NUM_WORKER": str(n_clients),
        "DMLC_NUM_SERVER": "1",
        "BYTEPS_FORCE_DISTRIBUTED": "1",
    })
    cfg = Config.from_env()
    srv = NativePSServer(cfg)
    threading.Thread(target=srv.start, daemon=True).start()
    clients = [PSClient(cfg, node_uid=f"ms{i}") for i in range(n_clients)]
    cts = [threading.Thread(target=c.connect, daemon=True) for c in clients]
    for t in cts:
        t.start()
    for t in cts:
        t.join(30)

    n = int(mbytes * 1e6) // 4 // n_keys
    keys = list(range(1, n_keys + 1))
    payload = np.random.default_rng(7).normal(size=n).astype(np.float32)
    init_ts = [
        threading.Thread(
            target=lambda c=c: [c.init_tensor(k, n, 0) for k in keys],
            daemon=True,
        )
        for c in clients
    ]
    for t in init_ts:
        t.start()
    for t in init_ts:
        t.join(30)

    def client_round(c, version):
        done = threading.Event()
        state = [2 * len(keys)]
        lock = threading.Lock()

        def dec(*_a):
            with lock:
                state[0] -= 1
                if state[0] == 0:
                    done.set()

        for k in keys:
            c.push(k, payload.data.cast("B"), 0, version, cb=dec)
        for k in keys:
            c.pull(k, version, dec)
        if not done.wait(120):
            raise RuntimeError("multistream round timed out")

    def all_round(version):
        errs = []

        def runner(c):
            try:
                client_round(c, version)
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        ts = [threading.Thread(target=runner, args=(c,), daemon=True)
              for c in clients]
        for t in ts:
            t.start()
        for t in ts:
            t.join(150)
        if errs or any(t.is_alive() for t in ts):
            raise RuntimeError(f"multistream round failed: {errs or 'hang'}")

    for w in range(2):  # warmup
        all_round(w + 1)
    t0 = time.perf_counter()
    for r in range(rounds):
        all_round(r + 3)
    dt = time.perf_counter() - t0

    for c in clients:
        c.close()
    srv.stop()
    sched.stop()
    mb = 2 * mbytes * n_clients * rounds  # every client pushes AND pulls
    return {
        "van": van,
        "engine": "native",
        "mode": f"multistream-{n_clients}c",
        "stripes": stripes,
        "keys": n_keys,
        "mb_per_s": round(mb / dt, 1),
        "round_ms": round(dt / rounds * 1e3, 2),
        "mbytes_payload_per_client": mbytes,
    }


def bench_raw_socket(mbytes: float, rounds: int) -> dict:
    """Upper bound: the same payload ping-ponged over a bare loopback TCP
    socket with no framing, demux, or KV logic — how much of the wire the
    van's Python hot path keeps (VERDICT r3 #5)."""
    import socket

    n = int(mbytes * 1e6)
    payload = bytearray(np.random.default_rng(0).bytes(n))
    buf = bytearray(n)
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)

    def echo():
        conn, _ = srv.accept()
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        b = bytearray(n)
        view = memoryview(b)
        try:
            while True:
                got = 0
                while got < n:
                    r = conn.recv_into(view[got:], n - got)
                    if not r:
                        return
                    got += r
                conn.sendall(b)
        except OSError:
            return

    t = threading.Thread(target=echo, daemon=True)
    t.start()
    cli = socket.create_connection(srv.getsockname())
    cli.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    view = memoryview(buf)

    def round_once():
        cli.sendall(payload)
        got = 0
        while got < n:
            r = cli.recv_into(view[got:], n - got)
            if not r:
                raise RuntimeError("raw echo died")
            got += r

    for _ in range(2):
        round_once()
    t0 = time.perf_counter()
    for _ in range(rounds):
        round_once()
    dt = time.perf_counter() - t0
    cli.close()
    srv.close()
    # memcpy bound for context (the shm van's theoretical ceiling)
    a = np.frombuffer(bytes(payload), np.uint8).copy()
    t0 = time.perf_counter()
    for _ in range(10):
        b = a.copy()
    memcpy_mb_s = 10 * mbytes / (time.perf_counter() - t0)
    del b
    return {
        "van": "raw-tcp-loopback",
        "engine": "none",
        "mb_per_s": round(2 * mbytes * rounds / dt, 1),
        "round_ms": round(dt / rounds * 1e3, 2),
        "mbytes_payload": mbytes,
        "memcpy_mb_per_s": round(memcpy_mb_s, 1),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mbytes", type=float, default=8.0)
    ap.add_argument("--rounds", type=int, default=20)
    ap.add_argument("--vans", default="tcp,uds,shm")
    ap.add_argument("--engines", default="python,native",
                    help="server data planes to cross with the vans")
    ap.add_argument("--clients", default="python",
                    help="worker data planes: python and/or native "
                         "(BYTEPS_NATIVE_CLIENT; tcp/uds vans only)")
    ap.add_argument("--contend", action="store_true",
                    help="run a GIL-monopolizing Python thread during the "
                         "timed rounds (the engine-load scenario the "
                         "native client exists for)")
    ap.add_argument("--raw", action="store_true",
                    help="also measure the bare-socket upper bound")
    ap.add_argument("--keys", type=int, default=1,
                    help="split the payload across N keys")
    ap.add_argument("--streams", default="1",
                    help="comma list of BYTEPS_TCP_STREAMS values (tcp only)")
    ap.add_argument("--multistream", type=int, default=0,
                    help="ALSO run N concurrent client connections against "
                    "one native server at each --multistream-stripes value "
                    "(the striped-reducer contended row; VAN_BENCH_r06)")
    ap.add_argument("--multistream-stripes", default="1,4",
                    help="comma list of BYTEPS_SERVER_STRIPES values for "
                    "the --multistream rows")
    args = ap.parse_args()
    if args.raw:
        print(json.dumps(bench_raw_socket(args.mbytes, args.rounds)))
    engines = [e.strip() for e in args.engines.split(",") if e.strip()]
    native_unix = False
    if "native" in engines:
        from byteps_tpu.native import HAVE_NATIVE, get_lib

        if not HAVE_NATIVE:
            print(json.dumps({"engine": "native", "skipped": "lib not built"}))
            engines = [e for e in engines if e != "native"]
        else:
            native_unix = hasattr(get_lib(), "bps_native_server_start_unix")
    clients = [cl.strip() for cl in args.clients.split(",") if cl.strip()]
    if "native" in clients:
        from byteps_tpu.native import get_lib

        lib = get_lib()
        if lib is None or not hasattr(lib, "bpsc_drain"):
            print(json.dumps({"client": "native", "skipped": "lib not built"}))
            clients = [cl for cl in clients if cl != "native"]
    for van in args.vans.split(","):
        van = van.strip()
        if van == "shm":
            import platform

            if platform.machine() not in ("x86_64", "AMD64", "i686"):
                print(json.dumps({"van": van, "skipped": "needs x86-64 TSO"}))
                continue
        stream_counts = (
            [int(s.strip()) for s in args.streams.split(",")]
            if van == "tcp" else [1]
        )
        for engine in engines:
            if engine == "native" and van != "tcp" and not native_unix:
                print(json.dumps({
                    "van": van, "engine": engine,
                    "skipped": "stale native lib (no unix/shm listener)",
                }))
                continue
            for client in clients:
                if client == "native" and van == "shm":
                    print(json.dumps({
                        "van": van, "client": client,
                        "skipped": "shm keeps the Python client "
                                   "(mmap bulk path is already zero-copy)",
                    }))
                    continue
                for streams in stream_counts:
                    print(json.dumps(bench_van(
                        van, args.mbytes, args.rounds, engine,
                        streams=streams, n_keys=args.keys,
                        client_kind=client, contend=args.contend,
                    )))
    if args.multistream > 0:
        from byteps_tpu.native import HAVE_NATIVE

        if not HAVE_NATIVE:
            print(json.dumps({"mode": "multistream",
                              "skipped": "lib not built"}))
            return
        for van in args.vans.split(","):
            van = van.strip()
            for stripes in (int(s.strip())
                            for s in args.multistream_stripes.split(",")):
                print(json.dumps(bench_multistream(
                    van, args.mbytes, args.rounds, args.multistream, stripes,
                )))


if __name__ == "__main__":
    main()
