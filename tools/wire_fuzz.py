#!/usr/bin/env python
"""Seeded wire-codec fuzzer: mutated frames must raise or checksum-reject.

The wire contract after the end-to-end integrity plane
(docs/robustness.md "Wire integrity") is: a frame that was truncated, or
had any bit past the fixed 32-byte header flipped, must NEVER be
silently accepted by a checksummed decode — it either fails framing
(truncation → short read) or fails the CRC32C
(transport.ChecksumError).  This tool proves that property by
construction over every Op codec:

1. **corpus** — one encoded frame per data-plane codec
   (PUSH ± trace, PULL, INIT, REGISTER_COMPRESSOR, FUSED push with a
   compressed member + span trailer, FUSED reply, RESYNC_QUERY/STATE,
   MIGRATE_STATE, WRONG_OWNER), checksums stamped;
2. **truncate** — every frame is cut at seeded points (and at every
   point in ``--exhaustive`` mode): decode must raise;
3. **bit-flip** — seeded single-bit flips at offsets ≥ 32: decode must
   raise ``ChecksumError`` (the flip may land in the trace block, the
   CRC field itself, or the payload — all covered);
4. **control leg** — the same flips against UNchecksummed frames with a
   payload are counted as ``baseline_silent``: they decode fine, which
   is exactly the hole the checksum closes (the run asserts this leg is
   non-empty — the fuzzer can tell silence from detection);
5. **body codecs** — decode_fused_push / decode_fused_reply /
   decode_resync_query / decode_resync_state / decode_migrate_state
   over truncated bodies must raise cleanly (ValueError/struct.error),
   never crash some other way and never return a result that claims
   MORE bytes than the truncated body holds.  (decode_wrong_owner is
   tolerant by contract — header ``version`` is authoritative — and is
   exercised for no-crash only.)
6. **lossless frames** — MIGRATE_STATE/RESYNC_STATE bodies shipped
   inside the wire lossless container (BYTEPS_WIRE_LOSSLESS): seeded
   truncations must reject; every bit flip past the header on a
   checksummed lossless frame must raise ``ChecksumError``
   SPECIFICALLY — the CRC32C is computed over the COMPRESSED bytes and
   verified BEFORE the container is decoded, so in-flight corruption
   never reaches the LZ layer; with the checksum stripped, corrupting
   the 10-byte container header (magic/version/raw_len) must raise
   ``LosslessError`` — the container itself fails closed on structural
   damage, and silent flips inside LZ literals are exactly the hole
   the outer CRC closes.

Deterministic per ``--seed``; tier-1 runs a small smoke
(tests/test_wire_integrity.py::test_wire_fuzz_smoke), CI or a human can
run bigger sweeps:

    python tools/wire_fuzz.py --seed 7 --flips 2000
    python tools/wire_fuzz.py --exhaustive       # every truncation point

Exit 0 = every mutation rejected (stats printed); exit 1 prints the
first silently-accepted mutation with enough detail to replay it.
"""

from __future__ import annotations

import argparse
import os
import random
import struct
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from byteps_tpu.comm.transport import (  # noqa: E402
    CHECKSUM_SIZE,
    ChecksumError,
    HEADER_SIZE,
    LOSSLESS_FLAG,
    LosslessError,
    Message,
    Op,
    decode_fused_push,
    decode_fused_reply,
    decode_migrate_state,
    decode_resync_query,
    decode_resync_state,
    decode_wrong_owner,
    encode_fused_push,
    encode_fused_reply,
    encode_migrate_state,
    encode_resync_query,
    encode_resync_state,
    encode_wrong_owner,
    recv_message,
)

#: exceptions that count as "rejected" — anything else is a crash bug
_REJECTS = (ChecksumError, ConnectionError, ValueError, struct.error)


class _ByteSock:
    """Just enough socket surface for transport's recv path: serves a
    fixed byte string, then EOF (recv_into returning 0 → the framing
    layer's ``peer closed``)."""

    def __init__(self, data: bytes) -> None:
        self._b = memoryview(bytes(data))
        self._off = 0

    def recv_into(self, view, nbytes: int = 0) -> int:
        n = nbytes or len(view)
        take = min(n, len(self._b) - self._off)
        if take <= 0:
            return 0
        view[:take] = self._b[self._off : self._off + take]
        self._off += take
        return take


def decode_frame(data: bytes) -> Message:
    """One frame through the live receive path (checksum verified)."""
    return recv_message(_ByteSock(data))


def _onebit_payload() -> bytes:
    # onebit-shaped codec bytes (f32 scale + sign words, LE) — the
    # compressed-member case where a single flipped bit amplifies
    # across the whole decoded tensor
    return struct.pack("<f", 0.5) + struct.pack("<II", 0xDEADBEEF, 0x01234567)


def frame_corpus(checksum: bool = True):
    """[(name, frame_bytes, payload_len)] — one per data-plane codec,
    mirroring the golden fixture shapes."""
    from byteps_tpu.common.types import DataType, RequestType, get_command_type

    cmd_raw = get_command_type(RequestType.DEFAULT_PUSH_PULL,
                               int(DataType.FLOAT32))
    cmd_comp = get_command_type(RequestType.COMPRESSED_PUSH_PULL,
                                int(DataType.FLOAT32))
    fused_body = encode_fused_push(
        [(301, cmd_comp, 5, _onebit_payload()), (302, cmd_raw, 5, bytes(range(32)))],
        span_ids=[0xC0FFEE01, 0xC0FFEE02],
    )
    migrate_meta = {
        "key": 7, "epoch": 3, "dtype": int(DataType.FLOAT32),
        "store_version": 4, "recv_count": 0,
        "push_seen": {"1": 4}, "init_done": {"1": 99},
        "compressor_kwargs": {}, "store_nbytes": 16, "accum_nbytes": 0,
    }
    frames = [
        ("PUSH", Message(Op.PUSH, key=42, payload=bytes(range(64)), seq=7,
                         cmd=cmd_raw, version=3, flags=1, checksum=checksum)),
        ("PUSH+trace", Message(Op.PUSH, key=42, payload=bytes(range(64)),
                               seq=7, cmd=cmd_raw, version=3, flags=1,
                               trace=(0x1111, 0x2222), checksum=checksum)),
        ("PUSH+onebit", Message(Op.PUSH, key=43, payload=_onebit_payload(),
                                seq=8, cmd=cmd_comp, version=3, flags=1,
                                checksum=checksum)),
        ("PULL", Message(Op.PULL, key=42, seq=9, cmd=cmd_raw, version=3,
                         checksum=checksum)),
        ("INIT", Message(Op.INIT, key=43, seq=10, flags=2, version=0xA0001,
                         payload=struct.pack("!QI", 32, 0),
                         checksum=checksum)),
        ("REGISTER_COMPRESSOR", Message(
            Op.REGISTER_COMPRESSOR, key=43, seq=11,
            payload=b"byteps_compressor_type=onebit", checksum=checksum)),
        ("FUSED", Message(Op.FUSED, key=301, payload=fused_body, seq=12,
                          cmd=2, flags=1, trace=(0x3333, 0x4444),
                          checksum=checksum)),
        ("FUSED-reply", Message(
            Op.FUSED, key=301, seq=12,
            payload=encode_fused_reply(
                [(301, 5, _onebit_payload()), (302, 5, bytes(range(32)))]
            ), checksum=checksum)),
        ("RESYNC_QUERY", Message(
            Op.RESYNC_QUERY, key=0, seq=13,
            payload=encode_resync_query(3, [7, 9]), checksum=checksum)),
        ("RESYNC_STATE", Message(
            Op.RESYNC_STATE, key=7, seq=13,
            payload=encode_resync_state({
                7: {"store_version": 4, "seen": 3, "recv_count": 1,
                    "init": True},
            }), checksum=checksum)),
        ("MIGRATE_STATE", Message(
            Op.MIGRATE_STATE, key=7, seq=14, version=3,
            payload=encode_migrate_state(migrate_meta, b"\x01" * 16, b""),
            checksum=checksum)),
        ("WRONG_OWNER", Message(
            Op.WRONG_OWNER, key=7, seq=15, version=3,
            payload=encode_wrong_owner(3, 1), checksum=checksum)),
    ]
    return [(name, m.encode(), len(m.payload)) for name, m in frames]


def lossless_corpus(checksum: bool = True):
    """[(name, frame_bytes, payload_offset)] — MIGRATE_STATE and
    RESYNC_STATE frames whose bodies ride the wire lossless container
    (``lossless=True`` forces the transform regardless of
    BYTEPS_WIRE_LOSSLESS, matching what a flag-stamped peer emits).
    ``payload_offset`` is where the container's 10-byte header starts
    inside the frame."""
    from byteps_tpu.common.types import DataType

    migrate_meta = {
        "key": 7, "epoch": 3, "dtype": int(DataType.FLOAT32),
        "store_version": 4, "recv_count": 0,
        "push_seen": {str(r): 4 for r in range(8)},
        "init_done": {str(r): 99 for r in range(8)},
        "compressor_kwargs": {}, "store_nbytes": 256, "accum_nbytes": 0,
    }
    resync_body = encode_resync_state({
        k: {"store_version": 4, "seen": 3, "recv_count": 1, "init": True}
        for k in range(32)
    })
    frames = [
        ("MIGRATE_STATE+lz", Message(
            Op.MIGRATE_STATE, key=7, seq=21, version=3,
            payload=encode_migrate_state(
                migrate_meta, b"\x01" * 256, b""),
            checksum=checksum, lossless=True)),
        ("RESYNC_STATE+lz", Message(
            Op.RESYNC_STATE, key=0, seq=22, payload=resync_body,
            checksum=checksum, lossless=True)),
    ]
    out = []
    for name, m in frames:
        raw_len = len(m.payload)
        frame = m.encode()
        off = HEADER_SIZE + (CHECKSUM_SIZE if checksum else 0)
        # the transform must actually have fired: flag stamped, body
        # smaller than the raw encoding (these JSON-ish bodies compress)
        assert frame[2] & LOSSLESS_FLAG, f"{name}: lossless flag missing"
        assert len(frame) - off < raw_len, f"{name}: container did not win"
        out.append((name, frame, off))
    return out


#: (decoder, encoded body, tolerant) per body codec — ``tolerant``
#: decoders define a fallback for garbage (only no-crash is asserted)
def body_corpus():
    fused_body = encode_fused_push(
        [(301, 3, 5, _onebit_payload()), (302, 0, 5, bytes(range(32)))],
        span_ids=[1, 2],
    )
    reply = encode_fused_reply([(301, 5, b"abcd"), (302, 5, b"")])
    meta = {"key": 7, "epoch": 3, "store_nbytes": 8, "accum_nbytes": 4}
    return [
        ("decode_fused_push", decode_fused_push, fused_body, False),
        ("decode_fused_reply", decode_fused_reply, reply, False),
        ("decode_resync_query", decode_resync_query,
         encode_resync_query(3, [7, 9]), False),
        ("decode_resync_state", decode_resync_state,
         encode_resync_state({7: {"store_version": 4}}), False),
        ("decode_migrate_state", decode_migrate_state,
         encode_migrate_state(meta, b"\x01" * 8, b"\x02" * 4), False),
        ("decode_wrong_owner", decode_wrong_owner,
         encode_wrong_owner(3, 1), True),
    ]


def run_fuzz(seed: int = 7, flips: int = 400, truncations: int = 200,
             exhaustive: bool = False) -> dict:
    """Run the sweep; raises AssertionError on the first silent accept.
    Returns stats."""
    rng = random.Random(seed)
    stats = {"frames": 0, "truncations": 0, "flips": 0,
             "baseline_silent": 0, "body_truncations": 0,
             "lossless_truncations": 0, "lossless_flips_crc": 0,
             "lossless_structural": 0}
    corpus = frame_corpus(checksum=True)
    stats["frames"] = len(corpus)

    # 1/2: checksummed frames — truncate + flip must always reject
    for name, frame, _plen in corpus:
        cuts = (range(len(frame)) if exhaustive else sorted(
            rng.randrange(len(frame))
            for _ in range(max(1, truncations // len(corpus)))
        ))
        for k in cuts:
            stats["truncations"] += 1
            try:
                decode_frame(frame[:k])
            except _REJECTS:
                continue
            raise AssertionError(
                f"SILENT ACCEPT: {name} truncated to {k}/{len(frame)} "
                f"bytes decoded without error (seed={seed})"
            )
        n_flips = max(1, flips // len(corpus))
        for _ in range(n_flips):
            stats["flips"] += 1
            idx = rng.randrange(HEADER_SIZE, len(frame))
            bit = 1 << rng.randrange(8)
            mutated = bytearray(frame)
            mutated[idx] ^= bit
            try:
                decode_frame(bytes(mutated))
            except ChecksumError:
                continue
            except _REJECTS:
                # e.g. a flip in a length-bearing payload region that
                # desyncs framing before the CRC is even compared —
                # cannot happen at frame level (length rides the
                # protected header-adjacent region), but a reject is a
                # reject
                continue
            raise AssertionError(
                f"SILENT ACCEPT: {name} with bit {bit:#04x} flipped at "
                f"offset {idx} decoded without error (seed={seed})"
            )

    # 3: the control leg — the same flips on UNchecksummed frames pass
    # silently (payload-carrying frames only); proves the harness can
    # tell detection from silence
    for name, frame, plen in frame_corpus(checksum=False):
        if not plen:
            continue
        idx = len(frame) - plen + rng.randrange(plen)
        mutated = bytearray(frame)
        mutated[idx] ^= 1 << rng.randrange(8)
        try:
            msg = decode_frame(bytes(mutated))
        except _REJECTS:
            continue  # some flips land in self-validating JSON bodies
        if bytes(msg.payload) != frame[len(frame) - plen:]:
            stats["baseline_silent"] += 1
    assert stats["baseline_silent"] > 0, (
        "control leg produced no silent corruption — the fuzzer cannot "
        "distinguish detection from an inert mutation engine"
    )

    # 4: body codecs over truncated bodies — clean rejection or a
    # result that fits inside the truncated bytes; never another crash
    for name, dec, body, tolerant in body_corpus():
        cuts = (range(len(body)) if exhaustive else sorted(
            rng.randrange(len(body)) for _ in range(16)
        ))
        for k in cuts:
            stats["body_truncations"] += 1
            try:
                dec(body[:k])
            except _REJECTS:
                continue
            except Exception as e:  # noqa: BLE001
                raise AssertionError(
                    f"CRASH: {name} raised {type(e).__name__} ({e}) on a "
                    f"{k}/{len(body)}-byte truncation (seed={seed})"
                ) from e
            if not tolerant and name == "decode_fused_push":
                # a successful decode of a cut body is legal only when
                # the cut removed optional trailer bytes
                members = decode_fused_push(body)
                consumed = 4 + sum(24 + len(p) for *_x, p in members)
                assert k >= consumed, (
                    f"SILENT ACCEPT: {name} decoded a {k}-byte prefix "
                    f"but members need {consumed} bytes (seed={seed})"
                )

    # 5: lossless frames — truncation rejects; on a CHECKSUMMED frame
    # every post-header flip must be a ChecksumError (CRC32C rides over
    # the compressed bytes and is verified BEFORE the container decode,
    # so corruption never reaches the LZ layer)
    for name, frame, off in lossless_corpus(checksum=True):
        cuts = (range(len(frame)) if exhaustive else sorted(
            rng.randrange(len(frame)) for _ in range(24)
        ))
        for k in cuts:
            stats["lossless_truncations"] += 1
            try:
                decode_frame(frame[:k])
            except _REJECTS:
                continue
            raise AssertionError(
                f"SILENT ACCEPT: {name} truncated to {k}/{len(frame)} "
                f"bytes decoded without error (seed={seed})"
            )
        for _ in range(max(1, flips // 8)):
            stats["lossless_flips_crc"] += 1
            idx = rng.randrange(HEADER_SIZE, len(frame))
            mutated = bytearray(frame)
            mutated[idx] ^= 1 << rng.randrange(8)
            try:
                decode_frame(bytes(mutated))
            except ChecksumError:
                continue
            except LosslessError as e:
                raise AssertionError(
                    f"CRC ORDER BROKEN: {name} flip at offset {idx} "
                    f"reached the container decode ({e}) before the "
                    f"checksum verify (seed={seed})"
                ) from e
            raise AssertionError(
                f"SILENT ACCEPT: {name} flip at offset {idx} decoded "
                f"without error (seed={seed})"
            )
    # the container's own fail-closed floor: with NO checksum, damage
    # to the 10-byte container header (magic/version/method/raw_len)
    # still raises LosslessError — never a wrong-length silent decode
    for name, frame, off in lossless_corpus(checksum=False):
        for idx in range(off, off + 10):
            for bit in range(8):
                stats["lossless_structural"] += 1
                mutated = bytearray(frame)
                mutated[idx] ^= 1 << bit
                try:
                    decode_frame(bytes(mutated))
                except LosslessError:
                    continue
                except _REJECTS:
                    continue
                raise AssertionError(
                    f"SILENT ACCEPT: {name} (no checksum) container "
                    f"header bit {bit} at offset {idx} decoded without "
                    f"error (seed={seed})"
                )
    return stats


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--flips", type=int, default=2000,
                    help="total seeded bit flips across the corpus")
    ap.add_argument("--truncations", type=int, default=600,
                    help="total seeded truncation points across the corpus")
    ap.add_argument("--exhaustive", action="store_true",
                    help="every truncation point of every frame/body")
    args = ap.parse_args(argv)
    try:
        stats = run_fuzz(seed=args.seed, flips=args.flips,
                         truncations=args.truncations,
                         exhaustive=args.exhaustive)
    except AssertionError as e:
        print(f"WIRE FUZZ FAILED: {e}")
        return 1
    print(
        "WIRE FUZZ OK: %(frames)d codecs, %(truncations)d truncations + "
        "%(flips)d bit-flips all rejected; %(body_truncations)d body "
        "truncations clean; %(baseline_silent)d checksum-off control flips "
        "passed silently (the hole BYTEPS_WIRE_CHECKSUM closes); lossless "
        "frames: %(lossless_truncations)d truncations rejected, "
        "%(lossless_flips_crc)d flips all ChecksumError (CRC verifies "
        "before container decode), %(lossless_structural)d container-header "
        "corruptions fail closed" % stats
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
